"""The JS/Node no-SDK plan (VERDICT r4 #7), runtime-gated.

The reference ships a JS ping-pong with shell e2e coverage
(``plans/example-js``, ``integration_tests/example_02_js_pingpong.sh``);
``plans/example-js/run`` here is a Node implementation of
``docs/INSTANCE_PROTOCOL.md`` — same flow as the proven Perl plan
(pair discovery over sync pubsub, REAL TCP ping/pong rounds, barriers,
run-events outcome publish). The e2e tests skip when no ``node``
runtime exists (this image ships none — install node in CI to run them
green there); the manifest/layout checks always run."""

import os
import shutil

import pytest

from testground_tpu.api import TestPlanManifest
from testground_tpu.cli.main import main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")

HAS_NODE = shutil.which("node") is not None


class TestPlanShape:
    """Runtime-independent checks — these gate the plan's packaging even
    where node is absent."""

    def test_manifest_parses_and_targets_exec_bin(self):
        m = TestPlanManifest.load_file(
            os.path.join(PLANS, "example-js", "manifest.toml")
        )
        assert m.name == "example-js"
        assert m.testcase_by_name("pingpong") is not None
        assert m.has_runner("local:exec")

    def test_entry_point_is_executable_node(self):
        run = os.path.join(PLANS, "example-js", "run")
        assert os.access(run, os.X_OK)
        with open(run) as f:
            first = f.readline()
        assert "node" in first, first  # #!/usr/bin/env node


def _run(instances, rounds=3):
    assert (
        main(["plan", "import", "--from", os.path.join(PLANS, "example-js")])
        == 0
    )
    return main(
        [
            "run", "single", "example-js:pingpong",
            "--builder", "exec:bin",
            "--runner", "local:exec",
            "-i", str(instances),
            "-tp", f"rounds={rounds}",
        ]
    )


@pytest.mark.skipif(not HAS_NODE, reason="no node runtime in this image")
class TestJsPingPong:
    def test_pairs_exchange_real_traffic(self, tg_home, tmp_path, capsys):
        """4 instances pair up over sync pubsub, exchange 3 TCP
        ping/pong rounds each, and all report success
        (example_02_js_pingpong.sh: ``assert_run_outcome_is success``)."""
        rc = _run(instances=4)
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "(outcome: success)" in out
        assert out.count("round 3 rtt:") == 2  # one dialer per pair
        assert "4/4" in out

    def test_odd_instance_count_runs_solo(self, tg_home, tmp_path, capsys):
        rc = _run(instances=3)
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "runs solo" in out
        assert "3/3" in out
