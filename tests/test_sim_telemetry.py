"""Sim telemetry plane (docs/OBSERVABILITY.md): per-tick device-side
counters flushed once per chunk, run-span tracing, the ``tg stats``
surface, and the zero-extra-host-syncs contract.

The reference ships runtime metrics to InfluxDB and a dashboard viewer
(``pkg/metrics/viewer.go``); here the jitted engine itself emits the
counter block, so these tests pin (a) the chunk-flush row schema, (b)
exact conservation against the run's final ``results()`` totals, and (c)
that telemetry adds NO blocking device→host sync beyond the done-flag
poll the loop already pays.
"""

import json
import os
import time
import urllib.request

import numpy as np
import pytest

from testground_tpu.api import RunGroup
from testground_tpu.config import EnvConfig
from testground_tpu.sim import engine as engine_mod
from testground_tpu.sim.engine import SimProgram, build_groups
from testground_tpu.sim.executor import load_sim_testcases
from testground_tpu.sim.telemetry import (
    SIM_SERIES_FILE,
    SPAN_FILE,
    TELEMETRY_FIXED_COLUMNS,
    rows_from_blocks,
    telemetry_totals,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


def make_groups(*counts, params=None):
    return build_groups(
        [
            RunGroup(id=f"g{i}", instances=c, parameters=dict(params or {}))
            for i, c in enumerate(counts)
        ]
    )


def plan_case(plan, case):
    return load_sim_testcases(os.path.join(PLANS, plan))[case]()


def collect_rows(prog, **run_kw):
    blocks = []
    res = prog.run(telemetry_cb=blocks.append, **run_kw)
    return res, rows_from_blocks(blocks, tuple(g.id for g in prog.groups))


class TestChunkFlushSchema:
    def test_row_schema_and_conservation(self):
        """Every decoded row carries the fixed columns plus a per-group
        live map, ticks are contiguous from 0, and the per-tick sums
        equal the run's cumulative results() totals exactly (the
        acceptance invariant)."""
        prog = SimProgram(
            plan_case("network", "ping-pong"),
            make_groups(4),
            chunk=16,
            telemetry=True,
        )
        res, rows = collect_rows(prog, max_ticks=512)
        assert rows, "telemetry produced no rows"
        for row in rows:
            for col in TELEMETRY_FIXED_COLUMNS:
                assert col in row, f"missing column {col}"
                assert isinstance(row[col], int)
            assert set(row["live"]) == {"g0"}
        assert [r["tick"] for r in rows] == list(range(len(rows)))
        totals = telemetry_totals(rows)
        assert totals["delivered"] == res["msgs_delivered"]
        assert totals["sent"] == res["msgs_sent"]
        assert totals["enqueued"] == res["msgs_enqueued"]
        assert totals["dropped"] == res["msgs_dropped"]
        assert totals["rejected"] == res["msgs_rejected"]
        # conservation: sent = enqueued + dropped + rejected, and the
        # calendar drains fully on a completed run
        assert (
            totals["sent"]
            == totals["enqueued"] + totals["dropped"] + totals["rejected"]
        )
        assert rows[-1]["cal_depth"] == res["cal_depth"] == 0

    def test_live_counts_track_completion(self):
        """live_<group> is the running-instance count — it must step
        down as instances freeze (terminal status) and reach 0 by the
        last row."""
        prog = SimProgram(
            plan_case("network", "ping-pong"),
            make_groups(8),
            chunk=8,
            telemetry=True,
        )
        res, rows = collect_rows(prog, max_ticks=512)
        assert (res["status"] == 1).all()
        live = [r["live"]["g0"] for r in rows]
        assert live[0] == 8
        assert live[-1] == 0
        assert all(a >= b for a, b in zip(live, live[1:]))

    def test_padding_rows_dropped_and_schema_matches_program(self):
        prog = SimProgram(
            plan_case("placebo", "ok"), make_groups(3), chunk=32,
            telemetry=True,
        )
        assert prog.telemetry_schema() == TELEMETRY_FIXED_COLUMNS + (
            "live_g0",
        )
        res, rows = collect_rows(prog, max_ticks=64)
        # placebo:ok finishes at tick 0: exactly one real row out of a
        # 32-tick chunk — the 31 padding rows (tick = -1) are dropped
        assert len(rows) == 1 and rows[0]["tick"] == 0

    def test_sharded_matches_unsharded(self):
        import jax

        devs = jax.devices()
        assert len(devs) == 8
        mesh = jax.sharding.Mesh(np.asarray(devs), ("i",))

        def run(mesh_):
            prog = SimProgram(
                plan_case("network", "ping-pong"),
                make_groups(16),
                chunk=16,
                mesh=mesh_,
                telemetry=True,
            )
            return collect_rows(prog, max_ticks=512)

        (_, rows_u), (_, rows_s) = run(None), run(mesh)
        assert rows_u == rows_s


class TestCounterCorrectness:
    """Exact counter values under drops, rejects, and frozen instances —
    deterministic single-message scenarios, not statistical checks."""

    def test_reject_drop_and_delivered_exact(self):
        from testground_tpu.sim.api import (
            FILTER_ACCEPT,
            FILTER_DROP,
            FILTER_REJECT,
            RUNNING,
            SUCCESS,
            Outbox,
            SimTestcase,
        )
        import jax.numpy as jnp

        class Filtered(SimTestcase):
            """Instance 0 sends one message per dst ∈ {1, 2, 3} at tick
            1: dst 1 is REJECTed, dst 2 DROPped, dst 3 delivered."""

            SHAPING = ("latency", "filter_rules")
            FILTER_RULES = 2
            MSG_WIDTH = 1
            OUT_MSGS = 3
            IN_MSGS = 4
            MAX_LINK_TICKS = 8

            def step(self, env, state, inbox, sync, t):
                is_sender = env.global_seq == 0
                ob = Outbox(
                    dst=jnp.asarray([1, 2, 3], jnp.int32),
                    payload=jnp.ones((3, 1), jnp.int32),
                    valid=jnp.full((3,), (t == 1) & is_sender, bool),
                )
                return self.out(
                    state,
                    status=jnp.where(t >= 4, SUCCESS, RUNNING),
                    outbox=ob,
                    net_rules=self.filter_rules(
                        (1, 2, FILTER_REJECT), (2, 3, FILTER_DROP)
                    ),
                    net_rules_valid=(t == 0) & is_sender,
                )

        prog = SimProgram(
            Filtered(), make_groups(4), chunk=8, telemetry=True
        )
        res, rows = collect_rows(prog, max_ticks=32)
        assert (res["status"] == 1).all()
        by_tick = {r["tick"]: r for r in rows}
        # tick 1: 3 sent, 1 enqueued, 1 rejected, 1 dropped
        assert by_tick[1]["sent"] == 3
        assert by_tick[1]["enqueued"] == 1
        assert by_tick[1]["rejected"] == 1
        assert by_tick[1]["dropped"] == 1
        assert by_tick[1]["cal_depth"] == 1
        assert by_tick[1]["bytes_enqueued"] == 256
        # tick 2: the accepted message arrives
        assert by_tick[2]["delivered"] == 1
        assert by_tick[2]["cal_depth"] == 0
        assert res["msgs_delivered"] == 1
        assert res["msgs_rejected"] == 1
        assert res["msgs_dropped"] == 1

    def test_frozen_instances_send_nothing(self):
        """A terminal (frozen) instance's sends are masked: after the
        senders finish, the sent counter must go to zero even though the
        step function keeps emitting an outbox."""
        from testground_tpu.sim.api import (
            RUNNING,
            SUCCESS,
            Outbox,
            SimTestcase,
        )
        import jax.numpy as jnp

        class EagerSender(SimTestcase):
            SHAPING = ("latency",)
            MSG_WIDTH = 1
            IN_MSGS = 4
            MAX_LINK_TICKS = 8

            def step(self, env, state, inbox, sync, t):
                # everyone "sends every tick" — but terminates at tick 2
                # except instance 3, which lingers until tick 5
                dst = jnp.mod(env.global_seq + 1, 4)
                ob = Outbox.single(dst, jnp.asarray([1]), True, 1, 1)
                done_at = jnp.where(env.global_seq == 3, 5, 2)
                return self.out(
                    state,
                    status=jnp.where(t >= done_at, SUCCESS, RUNNING),
                    outbox=ob,
                )

        prog = SimProgram(
            EagerSender(), make_groups(4), chunk=8, telemetry=True
        )
        res, rows = collect_rows(prog, max_ticks=32)
        by_tick = {r["tick"]: r for r in rows}
        assert by_tick[1]["sent"] == 4  # everyone still live
        assert by_tick[3]["sent"] == 1  # only instance 3 survives tick 2
        assert by_tick[3]["live"]["g0"] == 1
        # totals: ticks 0-2 × 4 senders + ticks 3-5 × 1 sender
        assert res["msgs_sent"] == 3 * 4 + 3 * 1
        # instance 3's terminal-tick send (tick 5) is enqueued but the
        # run completes before its delivery tick — cal_depth reports
        # exactly that stranded in-flight message
        assert res["cal_depth"] == 1
        assert res["msgs_enqueued"] - res["msgs_delivered"] == 1

    def test_sync_occupancy_columns(self):
        from testground_tpu.sim.api import (
            RUNNING,
            SUCCESS,
            SimTestcase,
        )
        import jax.numpy as jnp

        class Signaller(SimTestcase):
            STATES = ["ready"]
            TOPICS = ["news"]
            SHAPING = ("latency",)
            MAX_LINK_TICKS = 8

            def step(self, env, state, inbox, sync, t):
                return self.out(
                    state,
                    status=jnp.where(t >= 2, SUCCESS, RUNNING),
                    signals=jnp.where(
                        t == 0, self.signal("ready"), jnp.zeros((1,), jnp.int32)
                    ),
                    pub_payload=jnp.zeros((1, self.PUB_WIDTH), jnp.int32),
                    pub_valid=jnp.asarray([t == 1]),
                )

        prog = SimProgram(
            Signaller(), make_groups(5), chunk=8, telemetry=True
        )
        res, rows = collect_rows(prog, max_ticks=16)
        by_tick = {r["tick"]: r for r in rows}
        assert by_tick[0]["sync_signals"] == 5  # every instance signalled
        assert by_tick[0]["sync_pubs"] == 0
        assert by_tick[1]["sync_pubs"] == 5  # every instance published
        assert by_tick[2]["sync_signals"] == 5  # occupancy, not a rate


class TestZeroExtraSyncs:
    def test_telemetry_adds_no_host_syncs(self, monkeypatch):
        """The acceptance contract: one blocking device→host sync per
        chunk (the done-flag poll), telemetry on or off. The counter
        block rides the same dispatch result and is read after the poll
        — a copy, not a sync."""
        calls = {"n": 0}
        real = engine_mod._poll_done

        def counting(done):
            calls["n"] += 1
            return real(done)

        monkeypatch.setattr(engine_mod, "_poll_done", counting)

        def run(telemetry):
            calls["n"] = 0
            prog = SimProgram(
                plan_case("network", "ping-pong"),
                make_groups(4),
                chunk=16,
                telemetry=telemetry,
            )
            blocks = []
            res = prog.run(
                max_ticks=512,
                telemetry_cb=blocks.append if telemetry else None,
            )
            chunks = res["ticks"] // 16
            return calls["n"], chunks, blocks

        syncs_off, chunks_off, _ = run(False)
        syncs_on, chunks_on, blocks = run(True)
        assert chunks_on == chunks_off
        assert syncs_off == chunks_off  # exactly one poll per dispatch
        assert syncs_on == syncs_off  # telemetry adds ZERO syncs
        assert len(blocks) == chunks_on  # yet every chunk flushed


@pytest.fixture()
def sim_engine(tg_home):
    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.engine import Engine, EngineConfig
    from testground_tpu.sim.runner import SimJaxRunner

    env = EnvConfig.load()
    e = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    e.start_workers()
    yield e
    e.stop()


class TestRunArtifacts:
    def test_run_writes_series_spans_and_journal(self, sim_engine):
        """End-to-end through the engine: telemetry=true produces a
        schema-valid sim_timeseries.jsonl whose sums match the journal
        totals, a parseable run_spans.jsonl, and the journal's always-on
        observability floor (msgs_*, carry_bytes)."""
        from tests.test_sim_runner import run_sim
        from testground_tpu.engine import Outcome
        from testground_tpu.sdk.events import parse_event_line

        t = run_sim(
            sim_engine,
            "network",
            "ping-pong",
            instances=2,
            run_params={"telemetry": True, "chunk": 16},
        )
        assert t.outcome() == Outcome.SUCCESS
        journal = t.result["journal"]
        sim = journal["sim"]
        # always-on floor: totals + memory are present without opt-ins
        assert sim["carry_bytes"] > 0
        assert sim["msgs_delivered"] > 0
        run_dir = os.path.join(
            sim_engine.env.dirs.outputs(), "network", t.id
        )
        rows = [
            json.loads(line)
            for line in open(os.path.join(run_dir, SIM_SERIES_FILE))
        ]
        assert journal["telemetry"]["rows"] == len(rows)
        for row in rows:
            assert row["run"] == t.id
            assert row["plan"] == "network"
            assert row["case"] == "ping-pong"
            for col in TELEMETRY_FIXED_COLUMNS:
                assert isinstance(row[col], int)
            assert isinstance(row["live"], dict)
        assert (
            sum(r["delivered"] for r in rows) == sim["msgs_delivered"]
        )
        assert sum(r["dropped"] for r in rows) == sim["msgs_dropped"]
        assert journal["telemetry"]["totals"]["delivered"] == sim[
            "msgs_delivered"
        ]
        # run-span tracing: every line parses as an sdk event; the core
        # phases are present and the build span reports the carry bytes
        events = []
        for line in open(os.path.join(run_dir, SPAN_FILE)):
            parsed = parse_event_line(line)
            assert parsed is not None, line
            events.append(parsed[1])
        spans = {
            (e["type"], e["span"])
            for e in events
            if e["type"].startswith("span") or e["type"] == "point"
        }
        for phase in ("run", "build", "execute", "collect"):
            assert ("span_start", phase) in spans
            assert ("span_end", phase) in spans
        assert ("point", "chunk") in spans
        assert ("point", "compile") in spans
        build_end = next(
            e
            for e in events
            if e["type"] == "span_end" and e["span"] == "build"
        )
        assert build_end["carry_bytes"] == sim["carry_bytes"]

    def test_disable_metrics_wins_over_telemetry_flag(self, tg_home):
        """The composition's disable_metrics opt-out suppresses the
        whole plane — series file, journal section, spans — even with
        runner config telemetry = true (same rule as plan-metric
        sampling)."""
        import threading

        from testground_tpu.api import RunInput
        from testground_tpu.engine import Outcome
        from testground_tpu.rpc import discard_writer
        from testground_tpu.sim.executor import (
            SimJaxConfig,
            execute_sim_run,
        )

        env = EnvConfig.load()
        job = RunInput(
            run_id="nometrics",
            test_plan="placebo",
            test_case="ok",
            total_instances=2,
            groups=[
                RunGroup(
                    id="all",
                    instances=2,
                    artifact_path=os.path.join(PLANS, "placebo"),
                    parameters={},
                )
            ],
            env=env,
            disable_metrics=True,
        )
        job.runner_config = SimJaxConfig(telemetry=True, chunk=8)
        out = execute_sim_run(job, discard_writer(), threading.Event())
        assert out.result.outcome == Outcome.SUCCESS
        run_dir = os.path.join(env.dirs.outputs(), "placebo", "nometrics")
        assert not os.path.exists(os.path.join(run_dir, SIM_SERIES_FILE))
        assert not os.path.exists(os.path.join(run_dir, SPAN_FILE))
        assert "telemetry" not in out.result.journal

    def test_telemetry_off_writes_no_series(self, sim_engine):
        from tests.test_sim_runner import run_sim

        t = run_sim(sim_engine, "placebo", "ok", instances=2)
        run_dir = os.path.join(
            sim_engine.env.dirs.outputs(), "placebo", t.id
        )
        assert not os.path.exists(os.path.join(run_dir, SIM_SERIES_FILE))
        assert "telemetry" not in t.result["journal"]
        # the observability floor is still there
        assert t.result["journal"]["sim"]["carry_bytes"] > 0


class TestStatsSurface:
    @pytest.fixture()
    def daemon(self, tg_home):
        from testground_tpu.daemon import Daemon

        d = Daemon(env=EnvConfig.load(), listen="localhost:0")
        d.start()
        yield d
        d.stop()

    @pytest.fixture()
    def finished_task(self, daemon):
        from testground_tpu.client import Client

        client = Client(daemon.address)
        client.import_plan(os.path.join(PLANS, "network"))
        task_id = client.run(
            {
                "global": {
                    "plan": "network",
                    "case": "ping-pong",
                    "builder": "sim:plan",
                    "runner": "sim:jax",
                    "total_instances": 2,
                    "run_config": {"telemetry": True, "chunk": 16},
                },
                "groups": [{"id": "all", "instances": {"count": 2}}],
            }
        )
        deadline = time.time() + 180
        while time.time() < deadline:
            t = client.status(task_id)
            if t["states"][-1]["state"] in ("complete", "canceled"):
                assert t["outcome"] == "success"
                return task_id
            time.sleep(0.2)
        raise TimeoutError(task_id)

    def test_stats_route_and_client(self, daemon, finished_task):
        from testground_tpu.client import Client

        data = Client(daemon.address).stats(finished_task)
        assert data["task_id"] == finished_task
        assert data["plan"] == "network" and data["case"] == "ping-pong"
        assert data["outcome"] == "success"
        assert data["sim"]["msgs_delivered"] > 0
        assert data["sim"]["carry_bytes"] > 0
        assert data["telemetry"]["rows"] > 0
        assert data["events"]["all"]["success"] == 2

    def test_stats_route_404s_unknown_task(self, daemon):
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                daemon.address + "/stats?task_id=ghost", timeout=30
            )
        assert ei.value.code == 404

    def test_cli_stats_renders_summary(self, daemon, finished_task, capsys):
        """``tg stats <task>`` against the daemon renders the telemetry
        table (the acceptance criterion's CLI half)."""
        from testground_tpu.cli.main import main

        rc = main(["--endpoint", daemon.address, "stats", finished_task])
        assert rc == 0
        out = capsys.readouterr().out
        assert "messages" in out
        assert "delivered=" in out
        assert "network:ping-pong" in out
        assert "per-tick rows" in out

    def test_cli_status_telemetry_flag(self, daemon, finished_task, capsys):
        from testground_tpu.cli.main import main

        rc = main(
            [
                "--endpoint",
                daemon.address,
                "status",
                "-t",
                finished_task,
                "--telemetry",
            ]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "Telemetry:" in out and "delivered=" in out
