"""Message-lifecycle flight recorder + delivery-latency histograms
(docs/OBSERVABILITY.md): trace-plan selector lowering, per-tick event
rows (status / signal / send-with-fate / deliver), determinism under a
chaos schedule, the zero-overhead jaxpr contract, histogram correctness
(bin edges, clamp-to-last-bin, Σbins == delivered), the percentile
estimator, and the end-to-end artifact surface (``sim_trace.jsonl``,
Chrome-trace ``trace_events.json``, journal sections)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from testground_tpu.api import RunGroup
from testground_tpu.config import EnvConfig
from testground_tpu.sim.api import (
    FILTER_DROP,
    FILTER_REJECT,
    RUNNING,
    SUCCESS,
    Outbox,
    SimTestcase,
)
from testground_tpu.sim.engine import SimProgram, build_groups
from testground_tpu.sim.executor import load_sim_testcases
from testground_tpu.sim.telemetry import (
    LATENCY_BINS,
    latency_bin_edges,
    latency_percentiles,
)
from testground_tpu.sim.trace import (
    build_trace_plan,
    chrome_trace,
    events_from_blocks,
    parse_trace,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


def make_groups(*counts, params=None):
    return build_groups(
        [
            RunGroup(id=f"g{i}", instances=c, parameters=dict(params or {}))
            for i, c in enumerate(counts)
        ]
    )


def plan_case(plan, case):
    return load_sim_testcases(os.path.join(PLANS, plan))[case]()


def run_traced(prog, **run_kw):
    blocks = []
    res = prog.run(trace_cb=blocks.append, **run_kw)
    gids = {}
    for g in prog.groups:
        for i in range(g.offset, g.offset + g.count):
            gids[i] = g.id
    return res, events_from_blocks(blocks, lambda i: gids.get(i, ""))


# ------------------------------------------------------------- selectors


class TestTracePlan:
    def test_unknown_key_refused(self):
        with pytest.raises(ValueError, match="unknown key"):
            parse_trace({"instnaces": "0:2"})

    def test_bad_fraction_refused(self):
        with pytest.raises(ValueError, match="fraction"):
            parse_trace({"fraction": 1.5})

    def test_nothing_declared_lowers_to_none(self):
        assert build_trace_plan(make_groups(4), {}) is None
        assert build_trace_plan(make_groups(4), {"g0": {}}) is None

    def test_range_and_group_scoping(self):
        groups = make_groups(4, 4)
        # group-level table scopes to its own group (group-relative range)
        plan = build_trace_plan(groups, {"g1": {"instances": "1:3"}})
        assert plan.lanes.tolist() == [5, 6]
        # run-global table covers the whole axis
        plan = build_trace_plan(groups, {"": {"instances": "6:8"}})
        assert plan.lanes.tolist() == [6, 7]

    def test_tables_union(self):
        groups = make_groups(4, 4)
        plan = build_trace_plan(
            groups, {"g0": {"instances": "0:1"}, "g1": {"instances": "0:1"}}
        )
        assert plan.lanes.tolist() == [0, 4]

    def test_seeded_fraction_is_deterministic(self):
        groups = make_groups(16)
        a = build_trace_plan(groups, {"": {"fraction": 0.25, "seed": 7}})
        b = build_trace_plan(groups, {"": {"fraction": 0.25, "seed": 7}})
        assert a.lanes.tolist() == b.lanes.tolist()
        assert a.count == 4

    def test_oversized_selection_refused(self, monkeypatch):
        import testground_tpu.sim.trace as trace_mod

        monkeypatch.setattr(trace_mod, "MAX_TRACE_LANES", 2)
        with pytest.raises(ValueError, match="MAX_TRACE_LANES"):
            build_trace_plan(make_groups(4), {"": {"instances": "0:3"}})

    def test_group_layout_mismatch_refused(self):
        plan = build_trace_plan(make_groups(8), {"": {"instances": "0:2"}})
        with pytest.raises(ValueError, match="group layout"):
            SimProgram(
                plan_case("placebo", "ok"), make_groups(4), trace=plan
            )


# ------------------------------------------------------ latency histogram


class _TwoLatency(SimTestcase):
    """Two groups ping a same-group partner once: group 0 at 2 ms egress
    latency, group 1 at 9 ms — the bins and the receiver-group
    attribution are then exact."""

    MSG_WIDTH = 1
    OUT_MSGS = 1
    IN_MSGS = 4
    MAX_LINK_TICKS = 32
    SHAPING = ("latency",)

    def step(self, env, state, inbox, sync, t):
        lat = 2.0 if env.group.index == 0 else 9.0
        partner = env.group.offset + jnp.mod(
            env.group_seq + 1, env.group.count
        )
        ob = Outbox.single(partner, jnp.asarray([1]), t == 1, 1, 1)
        return self.out(
            state,
            status=jnp.where(t >= 16, SUCCESS, RUNNING),
            outbox=ob,
            net_shape=self.link_shape(latency_ms=lat),
            net_shape_valid=t == 0,
        )


class _BigDelay(SimTestcase):
    """One exchange at a latency past the last bin's lower edge
    (2^(LATENCY_BINS-1) ticks) — must clamp into the last bin."""

    MSG_WIDTH = 1
    OUT_MSGS = 1
    IN_MSGS = 4
    MAX_LINK_TICKS = (1 << (LATENCY_BINS - 1)) + 8
    SHAPING = ("latency",)
    DEFAULT_LINK = (float(1 << (LATENCY_BINS - 1)) + 2.0,) + (0.0,) * 6

    def step(self, env, state, inbox, sync, t):
        n = env.test_instance_count
        dst = jnp.mod(env.global_seq + 1, n)
        ob = Outbox.single(dst, jnp.asarray([1]), t == 1, 1, 1)
        got = state.get("got", jnp.asarray(False)) | (inbox.count > 0)
        return self.out(
            {"got": got},
            status=jnp.where(got, SUCCESS, RUNNING),
            outbox=ob,
        )

    def init(self, env):
        return {"got": jnp.asarray(False)}


class TestLatencyHistogram:
    def test_bin_edges_schema(self):
        edges = latency_bin_edges()
        assert len(edges) == LATENCY_BINS
        assert edges[0] == 1
        assert all(b == 2 * a for a, b in zip(edges, edges[1:]))

    def test_bins_and_receiver_group_attribution(self):
        prog = SimProgram(
            _TwoLatency(), make_groups(2, 2), chunk=8, telemetry=True
        )
        res = prog.run(max_ticks=64)
        hist = np.asarray(res["lat_hist"])
        assert hist.shape == (2, LATENCY_BINS)
        # group 0: delay 2 ticks → bin 1 ([2, 4)); group 1: 9 → bin 3
        want0 = np.zeros(LATENCY_BINS, int)
        want0[1] = 2
        want1 = np.zeros(LATENCY_BINS, int)
        want1[3] = 2
        assert hist[0].tolist() == want0.tolist()
        assert hist[1].tolist() == want1.tolist()
        # conservation: Σ bins == delivered, exactly
        assert hist.sum() == res["msgs_delivered"] == 4

    def test_clamp_to_last_bin(self):
        prog = SimProgram(
            _BigDelay(), make_groups(2), chunk=256, telemetry=True
        )
        res = prog.run(max_ticks=8192)
        assert (res["status"] == SUCCESS).all()
        hist = np.asarray(res["lat_hist"])
        assert hist.sum() == res["msgs_delivered"] == 2
        assert hist[0, LATENCY_BINS - 1] == 2  # everything in the last bin

    def test_conservation_on_real_plan(self):
        prog = SimProgram(
            plan_case("network", "ping-pong"),
            make_groups(4),
            chunk=16,
            telemetry=True,
        )
        res = prog.run(max_ticks=512)
        assert np.asarray(res["lat_hist"]).sum() == res["msgs_delivered"]

    def test_no_histogram_without_telemetry(self):
        prog = SimProgram(plan_case("placebo", "ok"), make_groups(2), chunk=8)
        res = prog.run(max_ticks=32)
        assert "lat_hist" not in res

    def test_percentile_estimator(self):
        # empty: count only
        assert latency_percentiles([0] * LATENCY_BINS, 1.0) == {"count": 0}
        # single hit bin [8, 16): every quantile lands inside it
        hist = [0] * LATENCY_BINS
        hist[3] = 100
        pct = latency_percentiles(hist, 2.0)  # tick_ms = 2
        assert pct["count"] == 100
        for q in ("p50_ms", "p95_ms", "p99_ms"):
            assert 8 * 2.0 <= pct[q] <= 16 * 2.0
        assert pct["p50_ms"] < pct["p95_ms"] < pct["p99_ms"]
        # open last bin values at its lower edge
        hist = [0] * LATENCY_BINS
        hist[-1] = 10
        pct = latency_percentiles(hist, 1.0)
        assert pct["p50_ms"] == float(1 << (LATENCY_BINS - 1))


# ------------------------------------------------------- flight recorder


class _OneShot(SimTestcase):
    """Instance 0 sends one message to 1 at tick 1; everyone succeeds at
    tick 5 — every event of the tiny timeline is then exactly known."""

    MSG_WIDTH = 1
    OUT_MSGS = 1
    IN_MSGS = 4
    MAX_LINK_TICKS = 8
    SHAPING = ("latency",)

    def step(self, env, state, inbox, sync, t):
        ob = Outbox.single(
            1, jnp.asarray([42]), (t == 1) & (env.global_seq == 0), 1, 1
        )
        return self.out(
            state, status=jnp.where(t >= 5, SUCCESS, RUNNING), outbox=ob
        )


class _Filtered(SimTestcase):
    """Instance 0 sends to {1, 2, 3} at tick 1 under rules REJECT [1,2)
    / DROP [2,3) — one send per fate."""

    SHAPING = ("latency", "filter_rules")
    FILTER_RULES = 2
    MSG_WIDTH = 1
    OUT_MSGS = 3
    IN_MSGS = 4
    MAX_LINK_TICKS = 8

    def step(self, env, state, inbox, sync, t):
        is_sender = env.global_seq == 0
        ob = Outbox(
            dst=jnp.asarray([1, 2, 3], jnp.int32),
            payload=jnp.ones((3, 1), jnp.int32),
            valid=jnp.full((3,), (t == 1) & is_sender, bool),
        )
        return self.out(
            state,
            status=jnp.where(t >= 4, SUCCESS, RUNNING),
            outbox=ob,
            net_rules=self.filter_rules(
                (1, 2, FILTER_REJECT), (2, 3, FILTER_DROP)
            ),
            net_rules_valid=(t == 0) & is_sender,
        )


class TestFlightRecorder:
    def test_event_timeline_exact(self):
        groups = make_groups(2)
        prog = SimProgram(
            _OneShot(),
            groups,
            chunk=8,
            trace=build_trace_plan(groups, {"": {"instances": "0:2"}}),
        )
        res, events = run_traced(prog, max_ticks=64)
        assert (res["status"] == SUCCESS).all()
        sends = [e for e in events if e["event"] == "send"]
        assert sends == [
            {
                "tick": 1,
                "instance": 0,
                "group": "g0",
                "event": "send",
                "dst": 1,
                "fate": "enqueued",
            }
        ]
        delivers = [e for e in events if e["event"] == "deliver"]
        assert delivers == [
            {
                "tick": 2,
                "instance": 1,
                "group": "g0",
                "event": "deliver",
                "src": 0,
            }
        ]
        status = [e for e in events if e["event"] == "status"]
        assert {(e["tick"], e["instance"]) for e in status} == {
            (5, 0),
            (5, 1),
        }
        assert all(
            e["prev"] == "running" and e["status"] == "success"
            for e in status
        )

    def test_send_fates(self):
        groups = make_groups(4)
        prog = SimProgram(
            _Filtered(),
            groups,
            chunk=8,
            trace=build_trace_plan(groups, {"": {"instances": "0:1"}}),
        )
        res, events = run_traced(prog, max_ticks=32)
        sends = {
            e["dst"]: e["fate"] for e in events if e["event"] == "send"
        }
        assert sends == {1: "rejected", 2: "dropped", 3: "enqueued"}

    def test_untraced_lanes_emit_nothing(self):
        groups = make_groups(4)
        prog = SimProgram(
            _Filtered(),
            groups,
            chunk=8,
            trace=build_trace_plan(groups, {"": {"instances": "2:3"}}),
        )
        _, events = run_traced(prog, max_ticks=32)
        assert {e["instance"] for e in events} <= {2}

    def test_deterministic_under_chaos(self):
        """Same seed + schedule → bit-identical event streams, with a
        crash/restart/loss-burst schedule live (the replayability
        contract the fault plane established, extended to the trace)."""
        from testground_tpu.sim.faults import build_fault_schedule

        groups = make_groups(4)
        faults = build_fault_schedule(
            groups,
            {
                "": [
                    {"kind": "crash", "start_ms": 4, "instances": "0:1"},
                    {"kind": "restart", "start_ms": 9, "instances": "0:1"},
                    {
                        "kind": "loss_burst",
                        "start_ms": 2,
                        "duration_ms": 12,
                        "loss": 60.0,
                    },
                ]
            },
            1.0,
        )

        def once():
            prog = SimProgram(
                plan_case("chaos", "chaos-barrier"),
                make_groups(4),
                chunk=8,
                faults=faults,
                trace=build_trace_plan(
                    groups, {"": {"instances": "0:2"}}
                ),
            )
            _, events = run_traced(prog, max_ticks=512, seed=3)
            return events

        a, b = once(), once()
        assert a == b
        assert any(e["event"] == "status" for e in a)  # the crash shows

    def test_sharded_matches_unsharded(self):
        """Trace rows gather from instance-sharded arrays; without the
        replication constraint the SPMD partitioner emitted corrupted
        partial-combined rows — pin bit-equality across layouts (the
        telemetry plane's cross-validation pattern)."""
        devs = jax.devices()
        assert len(devs) == 8
        mesh = jax.sharding.Mesh(np.asarray(devs), ("i",))
        groups = make_groups(16)
        plan = build_trace_plan(groups, {"": {"instances": "0:3"}})

        def run(mesh_):
            prog = SimProgram(
                plan_case("network", "ping-pong"),
                make_groups(16),
                chunk=16,
                mesh=mesh_,
                telemetry=True,
                trace=plan,
            )
            res, events = run_traced(prog, max_ticks=512)
            return res["lat_hist"], events

        (hist_u, ev_u), (hist_s, ev_s) = run(None), run(mesh)
        assert ev_u == ev_s
        assert hist_u == hist_s

    def test_chrome_trace_shape(self):
        groups = make_groups(2)
        prog = SimProgram(
            _OneShot(),
            groups,
            chunk=8,
            trace=build_trace_plan(groups, {"": {"instances": "0:2"}}),
        )
        _, events = run_traced(prog, max_ticks=64)
        doc = chrome_trace(events, [0, 1], {0: "g0[0] i0", 1: "g0[1] i1"}, 1.0)
        # valid Chrome trace-event JSON: serializable, traceEvents list,
        # every event carries the required keys
        parsed = json.loads(json.dumps(doc))
        assert isinstance(parsed["traceEvents"], list)
        names = {e["name"] for e in parsed["traceEvents"]}
        assert "thread_name" in names and "send→1 (enqueued)" in names
        for ev in parsed["traceEvents"]:
            for key in ("name", "ph", "pid", "tid"):
                assert key in ev
            if ev["ph"] == "i":
                assert "ts" in ev and ev["s"] == "t"


class TestZeroOverhead:
    def test_no_trace_traces_identically_to_baseline(self):
        """trace=None must produce the byte-identical traced chunk as a
        program built without the option (the acceptance contract), and
        an armed plan must change it — with and without telemetry."""
        groups = make_groups(4)
        tc = plan_case("network", "ping-pong")
        armed = build_trace_plan(groups, {"": {"instances": "0:1"}})
        for telemetry in (False, True):
            base = SimProgram(tc, groups, chunk=4, telemetry=telemetry)
            none = SimProgram(
                tc, groups, chunk=4, telemetry=telemetry, trace=None
            )
            on = SimProgram(
                tc, groups, chunk=4, telemetry=telemetry, trace=armed
            )
            carry = base.init_carry(0)
            j_base = str(jax.make_jaxpr(base._chunk_step)(carry))
            assert str(jax.make_jaxpr(none._chunk_step)(carry)) == j_base
            assert str(jax.make_jaxpr(on._chunk_step)(carry)) != j_base


# ------------------------------------------------------------ end-to-end


@pytest.fixture()
def sim_engine(tg_home):
    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.engine import Engine, EngineConfig
    from testground_tpu.sim.runner import SimJaxRunner

    env = EnvConfig.load()
    e = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    e.start_workers()
    yield e
    e.stop()


def run_traced_composition(engine, timeout=180):
    import time

    from testground_tpu.api import (
        Composition,
        Global,
        Group,
        Instances,
        TestPlanManifest,
        generate_default_run,
    )
    from testground_tpu.api.composition import RunParams
    from testground_tpu.engine import State

    comp = generate_default_run(
        Composition(
            global_=Global(
                plan="network",
                case="ping-pong",
                builder="sim:plan",
                runner="sim:jax",
                run_config={"telemetry": True, "chunk": 16},
            ),
            groups=[Group(id="all", instances=Instances(count=4))],
        )
    )
    comp.global_.run = RunParams(trace={"instances": "0:2"})
    manifest = TestPlanManifest.load_file(
        os.path.join(PLANS, "network", "manifest.toml")
    )
    tid = engine.queue_run(
        comp, manifest, sources_dir=os.path.join(PLANS, "network")
    )
    deadline = time.time() + timeout
    while time.time() < deadline:
        t = engine.get_task(tid)
        if t is not None and t.state().state in (
            State.COMPLETE,
            State.CANCELED,
        ):
            return t
        time.sleep(0.05)
    raise TimeoutError(tid)


class TestTraceE2E:
    def test_run_writes_trace_artifacts_and_journal(self, sim_engine):
        from testground_tpu.engine import Outcome
        from testground_tpu.sim.telemetry import LATENCY_FILE
        from testground_tpu.sim.trace import (
            TRACE_EVENTS_FILE,
            TRACE_FILE,
            read_trace_events,
        )

        t = run_traced_composition(sim_engine)
        assert t.outcome() == Outcome.SUCCESS
        journal = t.result["journal"]
        assert journal["trace"]["instances"] == 2
        assert journal["trace"]["events"] > 0
        assert journal["trace"]["file"] == TRACE_FILE
        assert journal["trace"]["events_file"] == TRACE_EVENTS_FILE
        # latency percentiles rode the telemetry plane into the journal
        lat = journal["sim"]["latency"]["all"]
        assert lat["count"] > 0 and lat["p50_ms"] > 0
        run_dir = os.path.join(
            sim_engine.env.dirs.outputs(), "network", t.id
        )
        # jsonl events match the journal count and the reader helper
        rows = [
            json.loads(line)
            for line in open(os.path.join(run_dir, TRACE_FILE))
        ]
        assert len(rows) == journal["trace"]["events"]
        assert {r["instance"] for r in rows} <= {0, 1}
        assert (
            read_trace_events(
                sim_engine.env.dirs.outputs(), "network", t.id
            )
            == rows
        )
        # Chrome export loads as valid trace-event JSON
        doc = json.load(open(os.path.join(run_dir, TRACE_EVENTS_FILE)))
        assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
        # latency rows are viewer-shaped and visible to the Viewer
        lat_rows = [
            json.loads(line)
            for line in open(os.path.join(run_dir, LATENCY_FILE))
        ]
        assert {r["name"] for r in lat_rows} == {
            "sim.latency.p50",
            "sim.latency.p95",
            "sim.latency.p99",
        }
        from testground_tpu.metrics import Viewer

        data = Viewer(sim_engine.env).get_data(
            "network", "ping-pong", "sim.latency.p50", run_id=t.id
        )
        assert len(data) == 1 and data[0].fields["mean"] == lat["p50_ms"]
        # stats payload carries both new sections
        stats = t.stats_payload()
        assert stats["trace"]["events"] == journal["trace"]["events"]
        assert stats["sim"]["latency"]["all"]["count"] == lat["count"]

    def test_no_trace_without_declaration(self, sim_engine):
        from tests.test_sim_runner import run_sim
        from testground_tpu.sim.trace import TRACE_FILE

        t = run_sim(sim_engine, "placebo", "ok", instances=2)
        run_dir = os.path.join(
            sim_engine.env.dirs.outputs(), "placebo", t.id
        )
        assert not os.path.exists(os.path.join(run_dir, TRACE_FILE))
        assert "trace" not in t.result["journal"]
