"""Sync service tests: in-memory semantics + TCP server/client
(the reference's sync-service contract, SURVEY.md §2.6)."""

import threading

import pytest

from testground_tpu.sync import InMemSyncService, SyncClient, SyncServiceServer


class TestInMem:
    def test_signal_entry_sequences(self):
        s = InMemSyncService()
        assert s.signal_entry("state") == 1
        assert s.signal_entry("state") == 2
        assert s.signal_entry("other") == 1

    def test_barrier_blocks_until_target(self):
        s = InMemSyncService()
        done = threading.Event()

        def waiter():
            s.barrier("go", 3, timeout=5)
            done.set()

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        s.signal_entry("go")
        s.signal_entry("go")
        assert not done.wait(timeout=0.2)
        s.signal_entry("go")
        assert done.wait(timeout=5)

    def test_barrier_timeout(self):
        s = InMemSyncService()
        with pytest.raises(TimeoutError):
            s.barrier("never", 1, timeout=0.1)

    def test_subscribe_sees_all_entries_in_order(self):
        """Every subscriber sees every entry (pingpong.go:219-244)."""
        s = InMemSyncService()
        s.publish("t", "a")
        s.publish("t", "b")
        got = []
        it = s.subscribe("t", timeout=1)
        got.append(next(it))
        got.append(next(it))
        s.publish("t", "c")
        got.append(next(it))
        assert got == ["a", "b", "c"]

    def test_signal_and_wait(self):
        s = InMemSyncService()
        results = []

        def party(i):
            results.append(s.signal_and_wait("sw", 3, timeout=5))

        threads = [threading.Thread(target=party, args=(i,)) for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5)
        assert sorted(results) == [1, 2, 3]


class TestTCP:
    """Protocol conformance, run against BOTH wire-compatible servers:
    the in-process Python one and the native C++ event-loop server
    (testground_tpu/native/syncsvc.cc)."""

    @pytest.fixture(scope="session")
    def native_bin_dir(self, tmp_path_factory):
        # one compile per test session: build_syncsvc caches by source
        # digest inside this dir
        return str(tmp_path_factory.mktemp("syncsvc-bin"))

    @pytest.fixture(params=["python", "native"])
    def server(self, request, native_bin_dir):
        if request.param == "native":
            from testground_tpu.native import (
                NativeSyncService,
                build_syncsvc,
                native_available,
            )

            if not native_available():
                pytest.skip("no C++ toolchain")
            srv = NativeSyncService(build_syncsvc(native_bin_dir))
            yield srv
            srv.stop()
        else:
            srv = SyncServiceServer().start()
            yield srv
            srv.stop()

    def test_client_roundtrip(self, server):
        host, port = server.address
        c1 = SyncClient(host, port, namespace="run:r1:")
        c2 = SyncClient(host, port, namespace="run:r1:")
        try:
            assert c1.signal_entry("s") == 1
            assert c2.signal_entry("s") == 2
            assert c1.counter("s") == 2

            c1.publish("topic", {"v": 1})
            c2.publish("topic", {"v": 2})
            it = c1.subscribe("topic", timeout=5)
            assert next(it) == {"v": 1}
            assert next(it) == {"v": 2}
        finally:
            c1.close()
            c2.close()

    def test_namespace_isolation(self, server):
        host, port = server.address
        a = SyncClient(host, port, namespace="run:a:")
        b = SyncClient(host, port, namespace="run:b:")
        try:
            a.signal_entry("s")
            assert b.counter("s") == 0
        finally:
            a.close()
            b.close()

    def test_signal_and_wait_across_clients(self, server):
        host, port = server.address
        clients = [
            SyncClient(host, port, namespace="run:x:") for _ in range(3)
        ]
        results = []

        def party(c):
            results.append(c.signal_and_wait("sw", 3, timeout=5))

        try:
            threads = [
                threading.Thread(target=party, args=(c,)) for c in clients
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=5)
            assert sorted(results) == [1, 2, 3]
        finally:
            for c in clients:
                c.close()

    def test_barrier_timeout_propagates(self, server):
        host, port = server.address
        c = SyncClient(host, port)
        try:
            with pytest.raises((RuntimeError, TimeoutError)):
                c.barrier("never", 1, timeout=0.1)
        finally:
            c.close()
