"""Integration tests: in-process engine + real plan processes via
``local:exec`` (SURVEY.md §4 tier 3 — the analog of
``pkg/integration/local_exec_test.go`` + ``integration_tests/03-05,14``)."""

import io
import os
import tarfile
import time

import pytest

from testground_tpu.api import (
    Composition,
    Global,
    Group,
    Instances,
    TestPlanManifest,
    generate_default_run,
)
from testground_tpu.engine import Engine, EngineConfig, Outcome, State
from testground_tpu.builders.exec_py import ExecPyBuilder
from testground_tpu.config import EnvConfig
from testground_tpu.runners.local_exec import LocalExecRunner

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


@pytest.fixture()
def engine(tg_home):
    env = EnvConfig.load()
    e = Engine(
        EngineConfig(
            env=env, builders=[ExecPyBuilder()], runners=[LocalExecRunner()]
        )
    )
    e.start_workers()
    yield e
    e.stop()


def run_plan(
    engine,
    plan,
    case,
    instances=1,
    params=None,
    timeout=60,
    run_config=None,
    builder="exec:py",
    runner="local:exec",
    profiles=None,
):
    comp = generate_default_run(
        Composition(
            global_=Global(
                plan=plan,
                case=case,
                builder=builder,
                runner=runner,
                run_config=dict(run_config or {}),
            ),
            groups=[Group(id="all", instances=Instances(count=instances))],
        )
    )
    if params:
        comp.runs[0].groups[0].test_params.update(params)
    if profiles:
        comp.runs[0].groups[0].profiles = dict(profiles)
    manifest = TestPlanManifest.load_file(
        os.path.join(PLANS, plan, "manifest.toml")
    )
    tid = engine.queue_run(comp, manifest, sources_dir=os.path.join(PLANS, plan))
    deadline = time.time() + timeout
    while time.time() < deadline:
        t = engine.get_task(tid)
        if t is not None and t.state().state in (State.COMPLETE, State.CANCELED):
            return t
        time.sleep(0.05)
    raise TimeoutError(f"task {tid} did not finish")


class TestPlacebo:
    def test_ok(self, engine):
        t = run_plan(engine, "placebo", "ok", instances=2)
        assert t.outcome() == Outcome.SUCCESS
        assert t.result["outcomes"]["all"] == {"total": 2, "ok": 2}

    def test_abort_fails(self, engine):
        t = run_plan(engine, "placebo", "abort")
        assert t.outcome() == Outcome.FAILURE

    def test_panic_fails(self, engine):
        t = run_plan(engine, "placebo", "panic")
        assert t.outcome() == Outcome.FAILURE

    def test_outputs_layout_and_collection(self, engine):
        """assert_run_output_is_correct semantics: run.out non-empty,
        run.err empty, layout <plan>/<run>/<group>/<instance>
        (header.sh:110-160, local_docker.go:258-267)."""
        t = run_plan(engine, "placebo", "ok", instances=2)
        out_root = engine.env.dirs.outputs()
        inst_dir = os.path.join(out_root, "placebo", t.id, "all", "0")
        assert os.path.isdir(inst_dir)
        assert os.path.getsize(os.path.join(inst_dir, "run.out")) > 0
        assert os.path.getsize(os.path.join(inst_dir, "run.err")) == 0

        buf = io.BytesIO()
        from testground_tpu.rpc import discard_writer

        engine.do_collect_outputs("local:exec", t.id, buf, discard_writer())
        buf.seek(0)
        with tarfile.open(fileobj=buf, mode="r:gz") as tar:
            names = tar.getnames()
        assert f"{t.id}/all/0/run.out" in names
        assert f"{t.id}/all/1/run.out" in names

    def test_metrics_written(self, engine):
        t = run_plan(engine, "placebo", "metrics")
        metrics = os.path.join(
            engine.env.dirs.outputs(), "placebo", t.id, "all", "0", "metrics.out"
        )
        assert os.path.getsize(metrics) > 0


class TestExample:
    def test_output(self, engine):
        t = run_plan(engine, "example", "output")
        assert t.outcome() == Outcome.SUCCESS

    def test_params_defaults_from_manifest(self, engine):
        t = run_plan(engine, "example", "params")
        assert t.outcome() == Outcome.SUCCESS
        run_out = os.path.join(
            engine.env.dirs.outputs(), "example", t.id, "all", "0", "run.out"
        )
        content = open(run_out).read()
        assert "default-2" in content  # manifest default applied

    def test_params_override(self, engine):
        t = run_plan(engine, "example", "params", params={"param2": "overridden"})
        content = open(
            os.path.join(
                engine.env.dirs.outputs(), "example", t.id, "all", "0", "run.out"
            )
        ).read()
        assert "overridden" in content

    def test_sync_leader_followers(self, engine):
        """Real multi-process coordination over the TCP sync service
        (plans/example/sync.go semantics)."""
        t = run_plan(engine, "example", "sync", instances=4, timeout=90)
        assert t.outcome() == Outcome.SUCCESS
        assert t.result["outcomes"]["all"] == {"total": 4, "ok": 4}

    def test_failure(self, engine):
        t = run_plan(engine, "example", "failure")
        assert t.outcome() == Outcome.FAILURE

    def test_artifact(self, engine):
        t = run_plan(engine, "example", "artifact")
        assert t.outcome() == Outcome.SUCCESS


class TestNativeSyncService:
    """The C++ sync service behind a full local:exec run (the sdk-side
    barrier/signal protocol of plans/example sync over the native
    server)."""

    def test_sync_plan_over_native_server(self, engine):
        from testground_tpu.native import native_available

        if not native_available():
            pytest.skip("no C++ toolchain")
        t = run_plan(
            engine,
            "example",
            "sync",
            instances=4,
            timeout=90,
            run_config={"sync_service": "native"},
        )
        assert t.outcome() == Outcome.SUCCESS
        assert t.result["outcomes"]["all"] == {"ok": 4, "total": 4}

    def test_python_backend_still_selectable(self, engine):
        t = run_plan(
            engine,
            "example",
            "sync",
            instances=3,
            timeout=90,
            run_config={"sync_service": "python"},
        )
        assert t.outcome() == Outcome.SUCCESS


class TestExecBinCppPlan:
    """A plan written in C++ with NO SDK bindings: exec:bin builds it via
    its build.sh and the instances speak the raw protocol (TEST_* env,
    stdout events, sync TCP) — the sdk-rust/js analog (reference
    plans/example-rust, integration_tests/example_01)."""

    @pytest.fixture()
    def bin_engine(self, tg_home):
        from testground_tpu.builders.exec_bin import ExecBinBuilder

        env = EnvConfig.load()
        e = Engine(
            EngineConfig(
                env=env,
                builders=[ExecBinBuilder()],
                runners=[LocalExecRunner()],
            )
        )
        e.start_workers()
        yield e
        e.stop()

    def test_cpp_sync_plan_end_to_end(self, bin_engine):
        from testground_tpu.native import native_available

        if not native_available():
            pytest.skip("no C++ toolchain")
        comp = generate_default_run(
            Composition(
                global_=Global(
                    plan="example-cpp",
                    case="sync",
                    builder="exec:bin",
                    runner="local:exec",
                ),
                groups=[Group(id="all", instances=Instances(count=3))],
            )
        )
        manifest = TestPlanManifest.load_file(
            os.path.join(PLANS, "example-cpp", "manifest.toml")
        )
        tid = bin_engine.queue_run(
            comp, manifest, sources_dir=os.path.join(PLANS, "example-cpp")
        )
        deadline = time.time() + 120
        t = None
        while time.time() < deadline:
            t = bin_engine.get_task(tid)
            if t is not None and t.state().state in (
                State.COMPLETE,
                State.CANCELED,
            ):
                break
            time.sleep(0.1)
        assert t is not None and t.state().state == State.COMPLETE, (
            t and t.error
        )
        assert t.outcome() == Outcome.SUCCESS, t.error
        assert t.result["outcomes"]["all"] == {"ok": 3, "total": 3}


class TestProfileCapture:
    def test_cpu_profile_written_per_instance(self, engine):
        """A group requesting a cpu profile gets a pstats dump in each
        instance's outputs dir (the sdk-go pprof analog, SURVEY §5)."""
        import pstats

        from testground_tpu.config import EnvConfig

        t = run_plan(
            engine, "placebo", "ok", instances=2, profiles={"cpu": "true"}
        )
        assert t.outcome() == Outcome.SUCCESS
        outputs = EnvConfig.load().dirs.outputs()
        for i in range(2):
            prof = os.path.join(
                outputs, "placebo", t.id, "all", str(i), "profile-cpu.pstats"
            )
            assert os.path.isfile(prof), prof
            # the testcase always makes calls, so an empty profile means
            # the profiler never ran
            assert pstats.Stats(prof).total_calls > 0
