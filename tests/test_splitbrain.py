"""splitbrain plan tests: dynamic region partitioning + accept/reject/drop
filters + heal (the sim analog of integration_tests/09-11 and the protocol
of /root/reference/plans/splitbrain/main.go)."""

import numpy as np
import pytest

from testground_tpu.sim.api import FAILURE, SUCCESS
from testground_tpu.sim.engine import SimProgram

from test_sim_engine import make_groups, mesh8, plan_case


def region_counts(n):
    return [sum(1 for x in range(1, n + 1) if x % 3 == r) for r in range(3)]


def regions(n):
    return np.asarray([(i + 1) % 3 for i in range(n)])


def run_case(case, n, mesh=None, **kw):
    prog = SimProgram(
        plan_case("splitbrain", case),
        make_groups(n),
        test_plan="splitbrain",
        test_case=case,
        mesh=mesh,
        chunk=32,
    )
    return prog.run(max_ticks=4096, **kw)


class TestSplitBrain:
    @pytest.mark.parametrize("case", ["accept", "reject", "drop"])
    def test_verdicts_all_success(self, case):
        res = run_case(case, 9)
        assert (res["status"] == SUCCESS).all(), res["status"]

    def test_reply_counts_respect_partition(self):
        n = 9
        res = run_case("drop", n)
        st = res["states"][0]
        reg = regions(n)
        n_a, n_b, _ = region_counts(n)
        np.testing.assert_array_equal(np.asarray(st["region"]), reg)
        replies = np.asarray(st["replies"])
        # A misses B's replies; B misses A's; C hears everyone. (The heal
        # phase adds replies after the verdict, so compare with >=.)
        expected = np.where(
            reg == 0, n - 1 - n_b, np.where(reg == 1, n - 1 - n_a, n - 1)
        )
        assert (replies >= expected).all()

    def test_reject_feedback_counts(self):
        """Region A sees exactly 2·|B| REJECTs (probes + replies toward B);
        drop sees none — PROHIBIT vs BLACKHOLE (link.go:187-217)."""
        n = 9
        n_b = region_counts(n)[1]
        rej = np.asarray(run_case("reject", n)["states"][0]["rejected_total"])
        drp = np.asarray(run_case("drop", n)["states"][0]["rejected_total"])
        reg = regions(n)
        np.testing.assert_array_equal(rej[reg == 0], 2 * n_b)
        np.testing.assert_array_equal(rej[reg != 0], 0)
        np.testing.assert_array_equal(drp, 0)

    def test_blocks_then_heals(self):
        """Drop-case SUCCESS is itself the block proof: the judge demands
        replies == (n−1) − |B| for region A, which an unblocked network
        would overshoot (n−1 ≠ n−1−|B| when |B| > 0) → FAILURE. The heal
        proof is every region-A instance's latched heal reply, which can
        only arrive after its filters were restored to ACCEPT."""
        n = 6
        res = run_case("drop", n)
        assert (res["status"] == SUCCESS).all()
        st = res["states"][0]
        reg = regions(n)
        assert region_counts(n)[0] > 0  # region A nonempty at this n
        assert np.asarray(st["heal_got"])[reg == 0].all()
        assert (np.asarray(st["phase"]) == 6).all()  # P_DONE

    def test_sharded_mesh_matches_single(self):
        n = 12
        res_m = run_case("reject", n, mesh=mesh8())
        res_s = run_case("reject", n)
        assert (res_m["status"] == SUCCESS).all()
        for key in ("region", "replies", "rejected_total", "heal_got"):
            np.testing.assert_array_equal(
                np.asarray(res_m["states"][0][key]),
                np.asarray(res_s["states"][0][key]),
                err_msg=key,
            )

    def test_4k_scale_smoke(self):
        """BASELINE config 4 shape at reduced-but-nontrivial scale in CI;
        the full 4k single-chip run happens in bench/TPU sessions."""
        n = 192
        res = run_case("drop", n)
        assert (res["status"] == SUCCESS).all()
