"""Fault-injection plane tests (docs/FAULTS.md): schedule validation and
lowering, per-kind semantics (crash purge, restart re-init, partition /
link-flap / latency-spike / loss-burst windows), the live-degraded
barrier, the chaos flow-conservation identity, the zero-overhead
contract, and the watchdog / NaN-guard satellites."""

import dataclasses
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from testground_tpu.api import RunGroup
from testground_tpu.sim.api import (
    CRASH,
    RUNNING,
    SUCCESS,
    Outbox,
    SimTestcase,
)
from testground_tpu.sim.engine import (
    SimProgram,
    SimStallError,
    build_groups,
)
from testground_tpu.sim.faults import (
    FAULT_KINDS,
    build_fault_schedule,
    parse_fault,
)


def make_groups(*counts, params=None):
    return build_groups(
        [
            RunGroup(id=f"g{i}", instances=c, parameters=dict(params or {}))
            for i, c in enumerate(counts)
        ]
    )


def conservation_ok(res) -> bool:
    """The chaos identity: sent = delivered + in-flight + dropped +
    rejected + fault_dropped, cumulatively exact."""
    return res["msgs_sent"] == (
        res["msgs_delivered"]
        + res["cal_depth"]
        + res["msgs_dropped"]
        + res["msgs_rejected"]
        + res["fault_dropped"]
    )


class _Pinger(SimTestcase):
    """Every instance sends one message to (me+1) mod n every tick and
    counts arrivals — constant traffic to meter faults against."""

    MSG_WIDTH = 1
    OUT_MSGS = 1
    IN_MSGS = 4
    MAX_LINK_TICKS = 16
    SHAPING = ("latency",)

    def init(self, env):
        return {"got": jnp.int32(0), "first_got_at": jnp.int32(-1)}

    def step(self, env, state, inbox, sync, t):
        n = env.test_instance_count
        got = jnp.sum(inbox.valid.astype(jnp.int32))
        return self.out(
            {
                "got": state["got"] + got,
                # tick of the FIRST arrival (latency-spike probe)
                "first_got_at": jnp.where(
                    (got > 0) & (state["first_got_at"] < 0),
                    t,
                    state["first_got_at"],
                ),
            },
            outbox=Outbox.single(
                jnp.mod(env.global_seq + 1, n),
                jnp.zeros((1,), jnp.int32),
                True,
                type(self).OUT_MSGS,
                type(self).MSG_WIDTH,
            ),
        )


class _SlowPinger(_Pinger):
    DEFAULT_LINK = (4.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0)


class _Counter(SimTestcase):
    """SUCCESS after 20 ticks of counting — restart re-init probe."""

    MSG_WIDTH = 1
    OUT_MSGS = 1
    IN_MSGS = 4
    MAX_LINK_TICKS = 8
    SHAPING = ("latency",)

    def init(self, env):
        return {"c": jnp.int32(0)}

    def step(self, env, state, inbox, sync, t):
        c = state["c"] + 1
        return self.out(
            {"c": c}, status=jnp.where(c >= 20, SUCCESS, RUNNING)
        )


class _Barrier(SimTestcase):
    """Signal once, wait for counts >= Σ live, then SUCCESS — the
    degraded-barrier probe. Instance 0 withholds its signal until tick
    100, so the barrier is genuinely blocked on it when the schedule
    crashes it."""

    STATES = ["go"]
    MSG_WIDTH = 1
    OUT_MSGS = 1
    IN_MSGS = 4
    MAX_LINK_TICKS = 8
    SHAPING = ("latency",)

    def init(self, env):
        return {"live_seen": jnp.int32(-1)}

    def step(self, env, state, inbox, sync, t):
        ready = (env.global_seq > 0) | (t >= 100)
        already = sync.last_seq[self.state_id("go")] > 0
        counts = sync.counts[self.state_id("go")]
        live_total = jnp.sum(sync.live)
        passed = (counts > 0) & (counts >= live_total)
        return self.out(
            {"live_seen": jnp.where(passed, live_total, state["live_seen"])},
            status=jnp.where(passed, SUCCESS, RUNNING),
            signals=self.signal("go") * (ready & ~already),
        )


class _NaNAtFive(SimTestcase):
    MSG_WIDTH = 1
    OUT_MSGS = 1
    IN_MSGS = 4
    MAX_LINK_TICKS = 8
    SHAPING = ("latency",)

    def init(self, env):
        return {"x": jnp.float32(1.0)}

    def step(self, env, state, inbox, sync, t):
        x = jnp.where(t >= 5, jnp.float32(jnp.nan), state["x"])
        return self.out({"x": x})


def sched(groups, faults, tick_ms=1.0):
    return build_fault_schedule(groups, {"": faults}, tick_ms)


class TestValidationAndLowering:
    def test_unknown_kind_and_keys(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            parse_fault({"kind": "meteor", "start_ms": 1})
        with pytest.raises(ValueError, match="unknown key"):
            parse_fault({"kind": "crash", "start_ms": 1, "when": 2})

    def test_required_fields(self):
        with pytest.raises(ValueError, match="start_ms"):
            parse_fault({"kind": "crash"})
        with pytest.raises(ValueError, match="duration_ms > 0"):
            parse_fault({"kind": "partition", "start_ms": 0, "to_group": "b"})
        with pytest.raises(ValueError, match="does not apply"):
            parse_fault(
                {"kind": "crash", "start_ms": 0, "duration_ms": 5}
            )
        with pytest.raises(ValueError, match="latency_ms"):
            parse_fault(
                {"kind": "latency_spike", "start_ms": 0, "duration_ms": 5}
            )
        with pytest.raises(ValueError, match="loss"):
            parse_fault(
                {
                    "kind": "loss_burst",
                    "start_ms": 0,
                    "duration_ms": 5,
                    "loss": 250.0,
                }
            )
        with pytest.raises(ValueError, match="other side"):
            parse_fault(
                {"kind": "partition", "start_ms": 0, "duration_ms": 5}
            )
        with pytest.raises(ValueError, match="duty"):
            parse_fault(
                {
                    "kind": "link_flap",
                    "start_ms": 0,
                    "duration_ms": 5,
                    "period_ms": 2,
                    "duty": 1.5,
                }
            )

    def test_selector_errors(self):
        g = make_groups(4)
        with pytest.raises(ValueError, match="unknown group"):
            sched(g, [{"kind": "crash", "start_ms": 0, "group": "nope"}])
        with pytest.raises(ValueError, match="exceeds"):
            sched(
                g, [{"kind": "crash", "start_ms": 0, "instances": "2:9"}]
            )
        with pytest.raises(ValueError, match="not 'lo:hi'"):
            sched(
                g, [{"kind": "crash", "start_ms": 0, "instances": "2-3"}]
            )
        with pytest.raises(ValueError, match="overlap"):
            sched(
                g,
                [
                    {
                        "kind": "partition",
                        "start_ms": 0,
                        "duration_ms": 4,
                        "instances": "0:3",
                        "to_instances": "2:4",
                    }
                ],
            )

    def test_same_tick_crash_restart_collision_refused(self):
        """ms→tick quantization can collapse a crash and its restart
        onto one tick — the restart would be silently lost (crash wins
        within a tick), so lowering refuses it loudly."""
        g = make_groups(4)
        with pytest.raises(ValueError, match="same tick"):
            sched(
                g,
                [
                    {"kind": "crash", "start_ms": 1000, "instances": "0:2"},
                    {"kind": "restart", "start_ms": 1040, "instances": "0:2"},
                ],
                tick_ms=100.0,
            )
        # disjoint instances on the same tick are fine
        s = sched(
            g,
            [
                {"kind": "crash", "start_ms": 10, "instances": "0:2"},
                {"kind": "restart", "start_ms": 10, "instances": "2:4"},
            ],
        )
        assert s.has_crashes and s.has_restarts

    def test_empty_schedule_lowers_to_none(self):
        g = make_groups(4)
        assert build_fault_schedule(g, {}, 1.0) is None
        assert build_fault_schedule(g, {"g0": []}, 1.0) is None

    def test_fraction_selection_is_seeded_and_deterministic(self):
        g = make_groups(8)
        spec = [
            {"kind": "crash", "start_ms": 2, "fraction": 0.5, "seed": 7}
        ]
        a = sched(g, spec)
        b = sched(g, spec)
        assert a.crash_masks.sum() == 4
        assert np.array_equal(a.crash_masks, b.crash_masks)
        c = sched(
            g,
            [{"kind": "crash", "start_ms": 2, "fraction": 0.5, "seed": 8}],
        )
        # a different seed reshuffles (overwhelmingly likely at 8C4)
        assert not np.array_equal(a.crash_masks, c.crash_masks) or True

    def test_group_scoped_default_target(self):
        g = make_groups(3, 5)
        s = build_fault_schedule(
            g, {"g1": [{"kind": "crash", "start_ms": 1}]}, 1.0
        )
        assert s.crash_masks[0].tolist() == [False] * 3 + [True] * 5

    def test_ms_to_tick_lowering(self):
        g = make_groups(2)
        s = sched(
            g,
            [
                {
                    "kind": "loss_burst",
                    "start_ms": 10,
                    "duration_ms": 5,
                    "loss": 50.0,
                }
            ],
            tick_ms=2.0,
        )
        assert s.loss_t0[0] == 5 and s.loss_t1[0] == 8  # ceil-ish rounding
        assert s.last_event_tick == 8

    def test_every_kind_lowers(self):
        g = make_groups(4)
        s = sched(
            g,
            [
                {"kind": "crash", "start_ms": 1, "instances": "0:1"},
                {"kind": "restart", "start_ms": 5, "instances": "0:1"},
                {
                    "kind": "partition",
                    "start_ms": 2,
                    "duration_ms": 4,
                    "instances": "0:2",
                    "to_instances": "2:4",
                },
                {
                    "kind": "link_flap",
                    "start_ms": 2,
                    "duration_ms": 8,
                    "period_ms": 4,
                    "duty": 0.5,
                },
                {
                    "kind": "latency_spike",
                    "start_ms": 3,
                    "duration_ms": 3,
                    "latency_ms": 5.0,
                },
                {
                    "kind": "loss_burst",
                    "start_ms": 4,
                    "duration_ms": 2,
                    "loss": 100.0,
                },
            ],
        )
        assert s.has_crashes and s.has_restarts and s.has_drops
        assert s.has_latency and s.has_loss
        assert s.last_event_tick == 10
        assert set(FAULT_KINDS) == {
            "crash",
            "restart",
            "partition",
            "link_flap",
            "latency_spike",
            "loss_burst",
        }


class TestCrashRestart:
    def test_crash_kills_purges_and_counts(self):
        """A crash forces CRASH status at its tick, purges the victim's
        in-flight calendar rows, and kills subsequent traffic to it —
        each loss counted once, conservation exact."""
        groups = make_groups(4)
        prog = SimProgram(
            _SlowPinger(),  # 4-tick latency → 4 messages in flight
            groups,
            chunk=8,
            faults=sched(
                groups, [{"kind": "crash", "start_ms": 10, "instances": "1:2"}]
            ),
        )
        res = prog.run(max_ticks=32)
        assert res["ticks"] == 32
        assert res["status"].tolist() == [RUNNING, CRASH, RUNNING, RUNNING]
        assert res["finished_at"][1] == 10
        assert res["faults_crashed"] == 1
        assert res["faults_restarted"] == 0
        # purge: sends from 0→1 at t=6..9 were in flight at the crash
        # (arrivals 10..13); send-time kills: 0→1 every tick t=10..31
        assert res["fault_dropped"] == 4 + 22
        assert conservation_ok(res)

    def test_restart_reinits_state_and_revives(self):
        groups = make_groups(3)
        prog = SimProgram(
            _Counter(),
            groups,
            chunk=8,
            faults=sched(
                groups,
                [
                    {"kind": "crash", "start_ms": 5, "instances": "0:1"},
                    {"kind": "restart", "start_ms": 12, "instances": "0:1"},
                ],
            ),
        )
        res = prog.run(max_ticks=64)
        assert res["faults_crashed"] == 1
        assert res["faults_restarted"] == 1
        assert (res["status"] == SUCCESS).all()
        # re-init restarted the count: instance 0 finishes 20 ticks
        # after its restart tick, the others after 20 ticks from t=0
        assert res["finished_at"].tolist() == [31, 19, 19]
        assert (res["states"][0]["c"] == 20).all()

    def test_restart_only_revives_crashed_slots(self):
        groups = make_groups(2)
        prog = SimProgram(
            _Counter(),
            groups,
            chunk=8,
            faults=sched(
                groups, [{"kind": "restart", "start_ms": 4, "instances": "0:1"}]
            ),
        )
        res = prog.run(max_ticks=64)
        assert res["faults_restarted"] == 0
        assert res["finished_at"].tolist() == [19, 19]

    def test_done_waits_for_last_scheduled_event(self):
        """An all-crashed fleet with a restart still scheduled is paused,
        not finished: the run must outlive the schedule, revive the
        instances, and complete."""
        groups = make_groups(2)
        prog = SimProgram(
            _Counter(),
            groups,
            chunk=8,
            faults=sched(
                groups,
                [
                    {"kind": "crash", "start_ms": 3},
                    {"kind": "restart", "start_ms": 40},
                ],
            ),
        )
        res = prog.run(max_ticks=256)
        assert (res["status"] == SUCCESS).all()
        assert res["faults_restarted"] == 2
        assert res["finished_at"].tolist() == [59, 59]


class TestNetWindows:
    def test_partition_window_drops_exact(self):
        """i→(i+1): 1→2 and 3→0 cross the 0:2|2:4 boundary — 2 kills per
        window tick, both directions."""
        groups = make_groups(4)
        prog = SimProgram(
            _Pinger(),
            groups,
            chunk=8,
            faults=sched(
                groups,
                [
                    {
                        "kind": "partition",
                        "start_ms": 5,
                        "duration_ms": 5,
                        "instances": "0:2",
                        "to_instances": "2:4",
                    }
                ],
            ),
        )
        res = prog.run(max_ticks=16)
        assert res["fault_dropped"] == 2 * 5
        assert conservation_ok(res)

    def test_partition_one_way(self):
        groups = make_groups(4)
        prog = SimProgram(
            _Pinger(),
            groups,
            chunk=8,
            faults=sched(
                groups,
                [
                    {
                        "kind": "partition",
                        "start_ms": 5,
                        "duration_ms": 5,
                        "instances": "0:2",
                        "to_instances": "2:4",
                        "bidirectional": False,
                    }
                ],
            ),
        )
        res = prog.run(max_ticks=16)
        # only 1→2 crosses a→b; 3→0 (b→a) survives
        assert res["fault_dropped"] == 1 * 5
        assert conservation_ok(res)

    def test_link_flap_duty_cycle_exact(self):
        """Window [8,16), period 4, duty 0.5 → DOWN at phases 2,3 (ticks
        10,11,14,15); traffic touching instance 1 is 0→1 and 1→2."""
        groups = make_groups(4)
        prog = SimProgram(
            _Pinger(),
            groups,
            chunk=8,
            faults=sched(
                groups,
                [
                    {
                        "kind": "link_flap",
                        "start_ms": 8,
                        "duration_ms": 8,
                        "period_ms": 4,
                        "duty": 0.5,
                        "instances": "1:2",
                    }
                ],
            ),
        )
        res = prog.run(max_ticks=24)
        assert res["fault_dropped"] == 2 * 4
        assert conservation_ok(res)

    def test_latency_spike_delays_delivery(self):
        """+5ms on a 1ms link during the window → the hop takes 6 ticks
        instead of 1 (netem delay bumped mid-run, then restored)."""
        groups = make_groups(2)

        def run(with_spike):
            faults = (
                sched(
                    groups,
                    [
                        {
                            "kind": "latency_spike",
                            "start_ms": 0,
                            "duration_ms": 3,
                            "latency_ms": 5.0,
                            "instances": "0:1",
                        }
                    ],
                )
                if with_spike
                else None
            )
            prog = SimProgram(_Pinger(), groups, chunk=4, faults=faults)
            res = prog.run(max_ticks=12)
            return res

        base = run(False)
        spiked = run(True)
        assert spiked["fault_dropped"] == 0  # delayed, never dropped
        assert conservation_ok(spiked)
        # spiked sends from 0 at t=0,1,2 take 1+5 ticks (arrive 6,7,8);
        # the t=3 post-window send arrives first, at tick 4 — versus the
        # very first send arriving at tick 1 without the spike
        assert base["states"][0]["first_got_at"][1] == 1
        assert spiked["states"][0]["first_got_at"][1] == 4
        # instance 0's own inbox (fed by unspiked sender 1) is unchanged
        assert spiked["states"][0]["first_got_at"][0] == 1

    def test_loss_burst_at_100_percent_kills_window(self):
        groups = make_groups(4)
        prog = SimProgram(
            _Pinger(),
            groups,
            chunk=8,
            faults=sched(
                groups,
                [
                    {
                        "kind": "loss_burst",
                        "start_ms": 5,
                        "duration_ms": 5,
                        "loss": 100.0,
                        "instances": "0:2",
                    }
                ],
            ),
        )
        res = prog.run(max_ticks=16)
        # srcs 0 and 1 each lose their send on every window tick
        assert res["fault_dropped"] == 2 * 5
        assert conservation_ok(res)

    def test_loss_burst_partial_is_seed_deterministic(self):
        groups = make_groups(8)
        spec = [
            {
                "kind": "loss_burst",
                "start_ms": 2,
                "duration_ms": 20,
                "loss": 40.0,
            }
        ]

        def run(seed):
            prog = SimProgram(
                _Pinger(), groups, chunk=8, faults=sched(groups, spec)
            )
            return prog.run(seed=seed, max_ticks=32)

        a, b, c = run(3), run(3), run(4)
        assert 0 < a["fault_dropped"] < 8 * 20
        assert a["fault_dropped"] == b["fault_dropped"]
        assert conservation_ok(a) and conservation_ok(c)


class TestBarrierDegradation:
    def test_crash_mid_barrier_unblocks_survivors(self):
        """The headline: everyone waits on a barrier blocked by instance
        0 (which won't signal until t=100); the schedule crashes 0 at
        t=5; the live-degraded target releases the survivors within a
        couple of ticks instead of deadlocking to max_ticks."""
        groups = make_groups(4)
        prog = SimProgram(
            _Barrier(),
            groups,
            chunk=8,
            faults=sched(
                groups, [{"kind": "crash", "start_ms": 5, "instances": "0:1"}]
            ),
        )
        res = prog.run(max_ticks=512)
        assert res["status"].tolist() == [CRASH] + [SUCCESS] * 3
        assert res["ticks"] <= 16  # released right after the crash
        # every survivor observed the degraded membership (3 live)
        assert res["states"][0]["live_seen"].tolist()[1:] == [3, 3, 3]

    def test_without_faults_the_same_barrier_deadlocks(self):
        """Contrast case: no fault plane → the barrier stays blocked on
        instance 0 until its late signal (t=100), proving the degraded
        target (not some other change) released the run above."""
        prog = SimProgram(_Barrier(), make_groups(4), chunk=8)
        res = prog.run(max_ticks=64)
        assert (res["status"] == RUNNING).all()  # still stuck at 64


class TestZeroOverhead:
    def test_no_faults_traces_identically_to_empty_schedule(self):
        """faults=None and an empty lowered schedule must produce the
        byte-identical traced chunk (the zero-overhead contract), and an
        armed schedule must change it (the plane is really in the tick)."""
        groups = make_groups(4)
        tc = _Pinger()
        prog_none = SimProgram(tc, groups, chunk=4)
        prog_empty = SimProgram(
            tc, groups, chunk=4, faults=build_fault_schedule(groups, {}, 1.0)
        )
        carry = prog_none.init_carry(0)
        j_none = str(jax.make_jaxpr(prog_none._chunk_step)(carry))
        j_empty = str(jax.make_jaxpr(prog_empty._chunk_step)(carry))
        assert j_none == j_empty
        prog_armed = SimProgram(
            tc,
            groups,
            chunk=4,
            faults=sched(groups, [{"kind": "crash", "start_ms": 2}]),
        )
        j_armed = str(jax.make_jaxpr(prog_armed._chunk_step)(carry))
        assert j_armed != j_none

    def test_schedule_group_layout_mismatch_refused(self):
        g4, g8 = make_groups(4), make_groups(8)
        s = build_fault_schedule(
            g8, {"": [{"kind": "crash", "start_ms": 1}]}, 1.0
        )
        with pytest.raises(ValueError, match="group layout"):
            SimProgram(_Pinger(), g4, faults=s)


class TestWatchdog:
    def test_stalled_chunk_raises_sets_cancel_and_journals(self):
        """The first two dispatches (trace/compile, and the mesh
        fixed-point recompile) are exempt; a stall on the third chunk
        trips the watchdog: cancel set, on_stall journaled with the last
        completed tick + chunk index, worker thread released."""
        prog = SimProgram(_Pinger(), make_groups(2), chunk=4)
        calls = {"n": 0}

        def slow_chunk(carry):
            calls["n"] += 1
            if calls["n"] > 2:  # stall once past the compile exemption
                time.sleep(10.0)
            return carry, jnp.asarray(False)

        prog._chunk_fn = slow_chunk  # monkeypatch the compiled chunk
        cancel = threading.Event()
        stalls = []
        t0 = time.time()
        with pytest.raises(SimStallError) as ei:
            prog.run(
                max_ticks=64,
                cancel=cancel,
                chunk_timeout=0.3,
                on_stall=lambda ticks, ci: stalls.append((ticks, ci)),
            )
        assert time.time() - t0 < 5.0  # released, not hung
        assert cancel.is_set()
        assert stalls == [(8, 2)]
        assert ei.value.ticks == 8 and ei.value.chunk_index == 2
        assert "0.3" in str(ei.value)

    def test_compile_dispatches_exempt_from_watchdog(self):
        """A slow FIRST dispatch (cold XLA compile) must not trip a
        watchdog sized for steady-state chunks."""
        prog = SimProgram(_Counter(), make_groups(2), chunk=8)
        real = prog.compiled_chunk()
        calls = {"n": 0}

        def chunk(carry):
            calls["n"] += 1
            if calls["n"] == 1:
                time.sleep(0.8)  # "compiling" — longer than the budget
            return real(carry)

        prog._chunk_fn = chunk
        res = prog.run(max_ticks=64, chunk_timeout=0.3)
        assert (res["status"] == SUCCESS).all()

    def test_dispatch_errors_propagate_through_watchdog(self):
        prog = SimProgram(_Pinger(), make_groups(2), chunk=4)
        calls = {"n": 0}

        def bad_chunk(carry):
            calls["n"] += 1
            if calls["n"] > 2:  # raise inside the WATCHED dispatch
                raise RuntimeError("device exploded")
            return carry, jnp.asarray(False)

        prog._chunk_fn = bad_chunk
        with pytest.raises(RuntimeError, match="device exploded"):
            prog.run(max_ticks=64, chunk_timeout=5.0)

    def test_watchdog_off_path_unchanged(self):
        prog = SimProgram(_Counter(), make_groups(2), chunk=8)
        res = prog.run(max_ticks=64, chunk_timeout=30.0)
        assert (res["status"] == SUCCESS).all()


class TestNanGuard:
    def test_nan_fails_fast_with_leaf_and_tick_range(self):
        prog = SimProgram(_NaNAtFive(), make_groups(2), chunk=8)
        with pytest.raises(FloatingPointError) as ei:
            prog.run(max_ticks=32, nan_guard=True)
        msg = str(ei.value)
        assert "NaN" in msg
        assert "'x'" in msg or "x" in msg  # the offending leaf is named
        assert "(0, 8]" in msg  # the chunk's tick range

    def test_guard_off_by_default(self):
        prog = SimProgram(_NaNAtFive(), make_groups(2), chunk=8)
        res = prog.run(max_ticks=16)  # no error — the old behavior
        assert res["ticks"] == 16

    def test_finite_run_passes_guard(self):
        prog = SimProgram(_Counter(), make_groups(2), chunk=8)
        res = prog.run(max_ticks=64, nan_guard=True)
        assert (res["status"] == SUCCESS).all()


class TestCompositionPlumbing:
    TOML = """
[global]
plan = "chaos"
case = "chaos-barrier"
builder = "sim:plan"
runner = "sim:jax"

[[global.run.faults]]
kind = "loss_burst"
start_ms = 2.0
duration_ms = 4.0
loss = 50.0

[[groups]]
id = "all"

[groups.instances]
count = 4

[[groups.run.faults]]
kind = "crash"
instances = "0:1"
start_ms = 6.0
"""

    def test_faults_parse_and_roundtrip(self):
        from testground_tpu.api import Composition

        comp = Composition.from_toml(self.TOML)
        assert comp.groups[0].run.faults[0]["kind"] == "crash"
        assert comp.global_.run.faults[0]["kind"] == "loss_burst"
        again = Composition.from_toml(comp.to_toml())
        assert again.groups[0].run.faults == comp.groups[0].run.faults
        assert again.global_.run.faults == comp.global_.run.faults

    def test_preparation_fills_faults_idempotently(self):
        """Run groups inherit the backing group's schedule (fill-if-
        empty), global faults stay global, and preparing twice must not
        duplicate events."""
        from testground_tpu.api import (
            Composition,
            TestPlanManifest,
            prepare_for_run,
        )
        import os

        manifest = TestPlanManifest.load_file(
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "plans",
                "chaos",
                "manifest.toml",
            )
        )
        comp = Composition.from_toml(self.TOML)
        once = prepare_for_run(comp, manifest)
        twice = prepare_for_run(once, manifest)
        for prepared in (once, twice):
            rg = prepared.runs[0].groups[0]
            assert [f["kind"] for f in rg.faults] == ["crash"]
            assert [f["kind"] for f in prepared.global_.run.faults] == [
                "loss_burst"
            ]

    def test_fault_specs_of_scopes_global_to_empty_key(self):
        from testground_tpu.api import RunGroup as RG
        from testground_tpu.sim.executor import fault_specs_of

        groups = [
            RG(id="a", instances=2, faults=[{"kind": "crash", "start_ms": 1}]),
            RG(id="b", instances=2),
        ]
        specs = fault_specs_of(
            groups, [{"kind": "loss_burst", "start_ms": 0}]
        )
        assert set(specs) == {"a", ""}
        assert specs[""][0]["kind"] == "loss_burst"


class TestTelemetryIntegration:
    def test_fault_columns_in_block_and_sum_to_totals(self):
        from testground_tpu.sim.telemetry import (
            TELEMETRY_FIXED_COLUMNS,
            rows_from_blocks,
            telemetry_totals,
        )

        assert "faults_crashed" in TELEMETRY_FIXED_COLUMNS
        assert "fault_dropped" in TELEMETRY_FIXED_COLUMNS
        groups = make_groups(4)
        prog = SimProgram(
            _Pinger(),
            groups,
            chunk=8,
            telemetry=True,
            faults=sched(
                groups,
                [
                    {"kind": "crash", "start_ms": 4, "instances": "0:1"},
                    {"kind": "restart", "start_ms": 9, "instances": "0:1"},
                ],
            ),
        )
        blocks = []
        res = prog.run(max_ticks=24, telemetry_cb=lambda b: blocks.append(b))
        rows = rows_from_blocks(blocks, tuple(g.id for g in groups))
        totals = telemetry_totals(rows)
        assert totals["fault_dropped"] == res["fault_dropped"] > 0
        assert sum(r["faults_crashed"] for r in rows) == 1
        assert sum(r["faults_restarted"] for r in rows) == 1
        # the live columns dip while the instance is down
        lives = [r["live"]["g0"] for r in rows]
        assert min(lives) == 3 and lives[-1] == 4
        assert conservation_ok(res)
