"""benchmarks plan tests: barrier, pingpong-flood, and the storm gossip
flood (sim twins of /root/reference/plans/benchmarks — benchmarks.go
barrier/startup, storm.go)."""

import numpy as np

from testground_tpu.sim.api import SUCCESS
from testground_tpu.sim.engine import SimProgram

from test_sim_engine import make_groups, mesh8, plan_case


def run_case(case, n, params=None, mesh=None, max_ticks=4096, chunk=64):
    prog = SimProgram(
        plan_case("benchmarks", case),
        make_groups(n, params=params),
        test_plan="benchmarks",
        test_case=case,
        mesh=mesh,
        chunk=chunk,
    )
    return prog.run(max_ticks=max_ticks)


class TestBarrier:
    def test_releases_all(self):
        res = run_case("barrier", 64, chunk=8)
        assert (res["status"] == SUCCESS).all()
        # everyone releases the tick after the counter fills
        assert (res["finished_at"] == res["finished_at"][0]).all()


class TestStorm:
    def test_all_bytes_flow(self):
        """Conservation: with IN_MSGS covering the fan-in, every chunk
        written lands at a receiver (storm.go's bytes.sent/bytes.read
        counters; TCP would deliver exactly as many)."""
        n = 24
        res = run_case(
            "storm",
            n,
            params={
                "conn_outgoing": "3",
                "conn_delay_ticks": "8",
                "data_size_kb": "16",
            },
        )
        assert (res["status"] == SUCCESS).all()
        st = res["states"][0]
        sent = 4096 * np.asarray(st["sent_chunks"]).sum()
        read = np.asarray(st["bytes_read"]).sum()
        assert sent == n * 3 * 4 * 4096  # 3 conns × 4 chunks × 4 KiB each
        assert read == sent

    def test_writes_gated_on_dials_barrier(self):
        """No chunk may arrive before every instance finished dialing
        (the outgoing-dials-done gate in storm.go): with a long dial
        jitter window, early connections must idle until the barrier."""
        n = 8
        res = run_case(
            "storm",
            n,
            params={
                "conn_outgoing": "2",
                "conn_delay_ticks": "64",
                "data_size_kb": "4",
            },
        )
        assert (res["status"] == SUCCESS).all()
        st = res["states"][0]
        # all writes happen after every delay elapsed → finished_at is
        # at least the max dial delay plus the chunk count
        delays = np.asarray(st["delays"])[:, :2]
        assert res["finished_at"].min() >= delays.max()

    def test_sharded_matches_single(self):
        n = 16
        params = {
            "conn_outgoing": "2",
            "conn_delay_ticks": "4",
            "data_size_kb": "8",
        }
        res_m = run_case("storm", n, params=params, mesh=mesh8())
        res_s = run_case("storm", n, params=params)
        assert (res_m["status"] == SUCCESS).all()
        for key in ("sent_chunks", "bytes_read", "targets"):
            np.testing.assert_array_equal(
                np.asarray(res_m["states"][0][key]),
                np.asarray(res_s["states"][0][key]),
                err_msg=key,
            )
