"""benchmarks plan tests: barrier, pingpong-flood, and the storm gossip
flood (sim twins of /root/reference/plans/benchmarks — benchmarks.go
barrier/startup, storm.go)."""

import numpy as np

from testground_tpu.sim.api import SUCCESS
from testground_tpu.sim.engine import SimProgram

from test_sim_engine import make_groups, mesh8, plan_case


def run_case(case, n, params=None, mesh=None, max_ticks=4096, chunk=64):
    prog = SimProgram(
        plan_case("benchmarks", case),
        make_groups(n, params=params),
        test_plan="benchmarks",
        test_case=case,
        mesh=mesh,
        chunk=chunk,
    )
    return prog.run(max_ticks=max_ticks)


class TestBarrier:
    def test_releases_all(self):
        res = run_case(
            "barrier", 64, params={"barrier_iterations": "3"}, chunk=8
        )
        assert (res["status"] == SUCCESS).all()
        # everyone releases the tick after the counter fills
        assert (res["finished_at"] == res["finished_at"][0]).all()

    def test_percent_timings(self):
        """The percent sweep emits barrier_time_{20..100}_percent with
        sane orderings: every percentile takes >= 1 tick (signal→count
        propagation) and all instances agree (lockstep release)."""
        from testground_tpu.sim.engine import build_groups

        n, iters = 32, 4
        res = run_case(
            "barrier", n, params={"barrier_iterations": str(iters)}, chunk=8
        )
        assert (res["status"] == SUCCESS).all()
        tc = plan_case("benchmarks", "barrier")
        groups = res["groups"]
        m = tc.collect_metrics(groups[0], res["states"][0], res["status"])
        for pct in (20, 40, 60, 80, 100):
            vals = np.asarray(m[f"barrier_time_{pct}_percent"])
            assert vals.shape == (n,)
            assert (vals == vals[0]).all(), pct
            assert vals[0] >= 1.0, pct

    def test_sharded_matches_single(self):
        params = {"barrier_iterations": "2"}
        res_m = run_case("barrier", 16, params=params, mesh=mesh8())
        res_s = run_case("barrier", 16, params=params)
        assert (res_m["status"] == SUCCESS).all()
        np.testing.assert_array_equal(
            np.asarray(res_m["states"][0]["sums"]),
            np.asarray(res_s["states"][0]["sums"]),
        )


class TestNetInit:
    def test_init_barrier_ticks(self):
        res = run_case("netinit", 48, chunk=8)
        assert (res["status"] == SUCCESS).all()
        init_at = np.asarray(res["states"][0]["init_at"])
        # everyone signals at t=0; counts visible at t=1 → release at 1
        assert (init_at == 1).all()


class TestNetLinkShape:
    def test_shaped_latency_verified(self):
        """SUCCESS requires the observed one-way delay to equal the shaped
        latency — the testcase self-verifies the shaping path."""
        res = run_case(
            "netlinkshape", 16, params={"latency_ms": "8"}, chunk=16
        )
        assert (res["status"] == SUCCESS).all()
        st = res["states"][0]
        delay = np.asarray(st["got_at"]) - np.asarray(st["sent_at"])
        assert (delay == 8).all()
        assert (np.asarray(st["cfg_at"]) == 1).all()

    def test_odd_count_last_instance_succeeds(self):
        res = run_case(
            "netlinkshape", 9, params={"latency_ms": "4"}, chunk=16
        )
        assert (res["status"] == SUCCESS).all()


class TestSubtree:
    def test_publish_receive_verified(self):
        """One elected publisher, everyone else consumes + verifies all 7
        size series; any checksum mismatch would FAILURE the subscriber."""
        n, iters = 6, 16
        res = run_case(
            "subtree", n, params={"subtree_iterations": str(iters)}
        )
        assert (res["status"] == SUCCESS).all()
        st = res["states"][0]
        # exactly one publisher streamed 7*iters entries
        pub_idx = np.asarray(st["pub_idx"])
        assert (pub_idx == 7 * iters).sum() == 1
        assert (pub_idx == 0).sum() == n - 1
        # every subscriber consumed every series in full
        got = np.asarray(st["got"])
        subs = pub_idx == 0
        assert (got[subs] == iters).all()
        assert not np.asarray(st["bad"]).any()

    def test_metrics_shape(self):
        n, iters = 4, 8
        res = run_case(
            "subtree", n, params={"subtree_iterations": str(iters)}
        )
        tc = plan_case("benchmarks", "subtree")
        m = tc.collect_metrics(
            res["groups"][0], res["states"][0], res["status"]
        )
        for size in (64, 128, 256, 512, 1024, 2048, 4096):
            recv = np.asarray(m[f"subtree_time_{size}_bytes_receive_ticks"])
            pub = np.asarray(m[f"subtree_time_{size}_bytes_publish_ticks"])
            # subscribers have receive timings, the publisher has NaN there
            # (a series can drain in 0 ticks when SUB_K covers it whole)
            assert np.isnan(recv).sum() == 1
            assert (recv[~np.isnan(recv)] >= 0).all()
            # publisher streams one entry/tick → mean publish time ~1 tick
            assert np.isnan(pub).sum() == n - 1
            np.testing.assert_allclose(pub[~np.isnan(pub)], 1.0)

    def test_sharded_matches_single(self):
        params = {"subtree_iterations": "8"}
        res_m = run_case("subtree", 8, params=params, mesh=mesh8())
        res_s = run_case("subtree", 8, params=params)
        assert (res_m["status"] == SUCCESS).all()
        for key in ("pub_idx", "got", "done_at"):
            np.testing.assert_array_equal(
                np.asarray(res_m["states"][0][key]),
                np.asarray(res_s["states"][0][key]),
                err_msg=key,
            )


class TestStorm:
    def test_all_bytes_flow(self):
        """Conservation: with IN_MSGS covering the fan-in, every chunk
        written lands at a receiver (storm.go's bytes.sent/bytes.read
        counters; TCP would deliver exactly as many)."""
        n = 24
        res = run_case(
            "storm",
            n,
            params={
                "conn_outgoing": "3",
                "conn_delay_ticks": "8",
                "data_size_kb": "16",
            },
        )
        assert (res["status"] == SUCCESS).all()
        st = res["states"][0]
        sent = 4096 * np.asarray(st["sent_chunks"]).sum()
        read = np.asarray(st["bytes_read"]).sum()
        assert sent == n * 3 * 4 * 4096  # 3 conns × 4 chunks × 4 KiB each
        assert read == sent

    def test_writes_gated_on_dials_barrier(self):
        """No chunk may arrive before every instance finished dialing
        (the outgoing-dials-done gate in storm.go): with a long dial
        jitter window, early connections must idle until the barrier."""
        n = 8
        res = run_case(
            "storm",
            n,
            params={
                "conn_outgoing": "2",
                "conn_delay_ticks": "64",
                "data_size_kb": "4",
            },
        )
        assert (res["status"] == SUCCESS).all()
        st = res["states"][0]
        # all writes happen after every delay elapsed → finished_at is
        # at least the max dial delay plus the chunk count
        delays = np.asarray(st["delays"])[:, :2]
        assert res["finished_at"].min() >= delays.max()

    def test_sharded_matches_single(self):
        n = 16
        params = {
            "conn_outgoing": "2",
            "conn_delay_ticks": "4",
            "data_size_kb": "8",
        }
        res_m = run_case("storm", n, params=params, mesh=mesh8())
        res_s = run_case("storm", n, params=params)
        assert (res_m["status"] == SUCCESS).all()
        for key in ("sent_chunks", "bytes_read", "targets"):
            np.testing.assert_array_equal(
                np.asarray(res_m["states"][0][key]),
                np.asarray(res_s["states"][0][key]),
                err_msg=key,
            )
