"""CLI-level end-to-end cases mirroring the reference's shell suite
(SURVEY.md §4 tier 4, ``integration_tests/*.sh``): plan scaffolding,
describe output, the task timeout, and the runner-disabled flag
(``18_runner_disabled.sh``; enforcement at ``supervisor.go:568-571``)."""

import os
import time


from testground_tpu.cli.main import main
from testground_tpu.engine import State

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


class TestPlanScaffold:
    def test_create_then_run(self, tg_home, capsys):
        """`tg plan create` scaffolds a plan that actually runs to SUCCESS
        (the reference's plan-templates flow, ``pkg/cmd/plan.go:25-74``)."""
        assert main(["plan", "create", "myplan"]) == 0
        out = capsys.readouterr().out
        assert "created plan myplan" in out

        assert (
            main(
                [
                    "run", "single", "myplan:ok",
                    "--builder", "exec:py", "--runner", "local:exec",
                    "-i", "2",
                ]
            )
            == 0
        )
        assert "outcome: success" in capsys.readouterr().out

    def test_create_refuses_existing(self, tg_home, capsys):
        assert main(["plan", "create", "dup"]) == 0
        capsys.readouterr()
        assert main(["plan", "create", "dup"]) != 0
        assert "already exists" in capsys.readouterr().err


class TestDescribe:
    def test_describe_plan_and_case(self, tg_home, capsys):
        main(["plan", "import", "--from", os.path.join(PLANS, "placebo")])
        capsys.readouterr()
        assert main(["describe", "placebo"]) == 0
        out = capsys.readouterr().out
        assert "placebo" in out and "ok" in out
        assert main(["describe", "placebo:stall"]) == 0
        assert "stall" in capsys.readouterr().out


class TestTaskTimeout:
    def test_stalling_task_is_canceled_at_timeout(self, tg_home, monkeypatch):
        """The worker cancels a task at the deadline (the reference's
        10-min default, ``supervisor.go:49-52``) — a stall plan must not
        hold the worker forever."""
        import testground_tpu.engine.supervisor as sup

        monkeypatch.setattr(sup, "DEFAULT_TASK_TIMEOUT_SECS", 3)

        from testground_tpu.builders.exec_py import ExecPyBuilder
        from testground_tpu.config import EnvConfig
        from testground_tpu.engine import Engine, EngineConfig
        from testground_tpu.runners.local_exec import LocalExecRunner
        from tests.test_local_exec import run_plan

        env = EnvConfig.load()
        # the config default is the reference's 10 minutes; route through
        # the patched fallback so the test finishes in seconds
        env.daemon.scheduler.task_timeout_min = 0
        e = Engine(
            EngineConfig(
                env=env,
                builders=[ExecPyBuilder()],
                runners=[LocalExecRunner()],
            )
        )
        e.start_workers()
        try:
            t0 = time.time()
            t = run_plan(e, "placebo", "stall", timeout=30)
            took = time.time() - t0
            assert t.state().state == State.CANCELED
            assert took < 25, f"timeout did not fire promptly ({took:.1f}s)"
        finally:
            e.stop()


class TestCIMetadata:
    def test_metadata_flags_recorded_on_task(self, tg_home, capsys):
        """--metadata-repo/branch/commit flow into the task's CreatedBy
        (``pkg/cmd/run.go:62-70`` → ``task.go:48-53``), the identity the
        queue's per-branch CI dedup keys on."""
        from testground_tpu.config import EnvConfig
        from testground_tpu.engine import Engine

        main(["plan", "import", "--from", os.path.join(PLANS, "placebo")])
        capsys.readouterr()
        rc = main(
            [
                "run", "single", "placebo:ok",
                "--builder", "exec:py", "--runner", "local:exec", "-i", "1",
                "--metadata-repo", "org/repo",
                "--metadata-branch", "main",
                "--metadata-commit", "abc123",
            ]
        )
        out = capsys.readouterr().out
        assert rc == 0
        task_id = out.split("run is queued with ID:")[1].split()[0]
        # fresh engine over the same disk store reads the archived task
        # (the CLI upgrades the default store to disk; mirror that here)
        env = EnvConfig.load()
        env.daemon.scheduler.task_repo_type = "disk"
        e = Engine.new_default(env)
        try:
            t = e.get_task(task_id)
            assert t.created_by.repo == "org/repo"
            assert t.created_by.branch == "main"
            assert t.created_by.commit == "abc123"
            assert t.created_by_ci()
        finally:
            e.stop()


class TestRunnerDisabled:
    def test_disabled_runner_is_refused(self, tg_home, capsys):
        """A runner disabled in .env.toml must refuse runs with a clear
        error (``RunnerDisabledFlag``, enforced ``supervisor.go:568-571``)."""
        with open(os.path.join(tg_home, ".env.toml"), "w") as f:
            f.write('[runners."local:exec"]\ndisabled = true\n')
        main(["plan", "import", "--from", os.path.join(PLANS, "placebo")])
        capsys.readouterr()
        rc = main(
            [
                "run", "single", "placebo:ok",
                "--builder", "exec:py", "--runner", "local:exec",
                "-i", "1",
            ]
        )
        cap = capsys.readouterr()
        assert rc != 0
        assert "outcome: failure" in cap.out
        # the refusal reason is surfaced in the streamed task output
        assert "disabled" in (cap.out + cap.err).lower()
        # ... and in the task status error field
        task_id = cap.out.split("run is queued with ID:")[1].split()[0]
        assert main(["status", "-t", task_id]) == 0
        assert "disabled in .env.toml" in capsys.readouterr().out


class TestTerminate:
    """`tg terminate` takes a runner OR a builder, one at a time
    (``terminate.go:38-45``; engine dispatch ``engine.go:285-311``)."""

    def test_requires_exactly_one_component(self, tg_home, capsys):
        assert main(["terminate"]) == 1
        assert (
            main(["terminate", "--runner", "local:exec", "--builder", "exec:py"])
            == 1
        )
        assert "exactly one" in capsys.readouterr().err

    def test_terminate_runner(self, tg_home, capsys):
        assert main(["terminate", "--runner", "local:exec"]) == 0
        assert "all jobs terminated" in capsys.readouterr().out

    def test_terminate_builder(self, tg_home, capsys):
        """Builders are terminatable (no-op — snapshot builds run
        synchronously with no external jobs), so the reference's
        --builder surface succeeds (``engine.go:285-311``)."""
        assert main(["terminate", "--builder", "exec:py"]) == 0
        assert "all jobs terminated" in capsys.readouterr().out

    def test_unknown_component_errors(self, tg_home, capsys):
        assert main(["terminate", "--runner", "nope:nope"]) == 1
        assert "unknown component" in capsys.readouterr().err


class TestPlanImportGit:
    def test_import_from_local_git_repo(self, tg_home, tmp_path, capsys):
        """`tg plan import --git --from <url>` clones through git (any
        scheme git supports — the reference's go-git path, plan.go:210-214)
        and then the plan runs."""
        import subprocess

        repo = tmp_path / "gitplan"
        repo.mkdir()
        src = os.path.join(PLANS, "placebo")
        for fname in ("main.py", "manifest.toml"):
            with open(os.path.join(src, fname)) as f:
                (repo / fname).write_text(f.read())
        env = {**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
               "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"}
        for cmd in (
            ["git", "init", "-q"],
            ["git", "add", "-A"],
            ["git", "commit", "-q", "-m", "plan"],
        ):
            subprocess.run(cmd, cwd=repo, check=True, env=env)

        assert main(["plan", "import", "--git", "--from", str(repo),
                     "--name", "gitbebo"]) == 0
        out = capsys.readouterr().out
        assert "imported plan gitbebo" in out
        # no .git directory is imported, and the plan actually runs
        plan_dir = os.path.join(str(tg_home), "plans", "gitbebo")
        assert not os.path.isdir(os.path.join(plan_dir, ".git"))
        assert main(["run", "single", "gitbebo:ok", "--builder", "exec:py",
                     "--runner", "local:exec", "-i", "1"]) == 0
        assert "outcome: success" in capsys.readouterr().out

    def test_git_import_rejects_repo_without_manifest(
        self, tg_home, tmp_path, capsys
    ):
        import subprocess

        repo = tmp_path / "notaplan"
        repo.mkdir()
        (repo / "README.md").write_text("nope")
        env = {**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
               "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"}
        for cmd in (
            ["git", "init", "-q"],
            ["git", "add", "-A"],
            ["git", "commit", "-q", "-m", "x"],
        ):
            subprocess.run(cmd, cwd=repo, check=True, env=env)
        assert main(["plan", "import", "--git", "--from", str(repo)]) == 1
        assert "manifest.toml" in capsys.readouterr().err


class TestRunFlags:
    """`tg run single` parity flags: --use-build, --run-cfg,
    --disable-metrics (``run.go:83-140``)."""

    def test_use_build_reuses_artifact(self, tg_home, capsys):
        main(["plan", "import", "--from", os.path.join(PLANS, "placebo")])
        capsys.readouterr()
        assert main(["build", "single", "placebo", "--builder", "exec:py"]) == 0
        out = capsys.readouterr().out
        artifact = out.split("group single artifact:")[1].split()[0]
        assert os.path.isfile(artifact)

        rc = main(
            [
                "run", "single", "placebo:ok",
                "--builder", "exec:py", "--runner", "local:exec",
                "-i", "1", "--use-build", artifact,
            ]
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert "outcome: success" in out
        # no new build happened: the run reused the prebuilt artifact
        assert "built: artifact" not in out

    def test_run_cfg_overrides_runner_config(self, tg_home, capsys):
        """--run-cfg trims the sim tick budget, so a stalling plan fails
        fast instead of burning the default 100k-tick budget."""
        main(["plan", "import", "--from", os.path.join(PLANS, "placebo")])
        capsys.readouterr()
        rc = main(
            [
                "run", "single", "placebo:stall",
                "--builder", "sim:plan", "--runner", "sim:jax",
                "-i", "4", "--run-cfg", "max_ticks=8",
                "--run-cfg", "chunk=4",
            ]
        )
        out = capsys.readouterr().out
        assert rc != 0
        assert "outcome: failure" in out

    def test_disable_metrics_reaches_the_instances(self, tg_home, capsys):
        """--disable-metrics lands in the composition and the instances'
        TEST_DISABLE_METRICS env. Semantics follow sdk-go: diagnostics
        batching is disabled, results (R()) still write metrics.out."""
        from testground_tpu.config import EnvConfig
        from testground_tpu.engine import Engine

        main(["plan", "import", "--from", os.path.join(PLANS, "placebo")])
        capsys.readouterr()
        rc = main(
            [
                "run", "single", "placebo:metrics",
                "--builder", "exec:py", "--runner", "local:exec",
                "-i", "1", "--disable-metrics",
            ]
        )
        out = capsys.readouterr().out
        assert rc == 0, out
        task_id = out.split("run is queued with ID:")[1].split()[0]
        env = EnvConfig.load()
        env.daemon.scheduler.task_repo_type = "disk"
        e = Engine.new_default(env)
        try:
            t = e.get_task(task_id)
            comp = t.result["composition"]
            assert comp["global"]["disable_metrics"] is True
        finally:
            e.stop()
        # results metrics still recorded (R() is not what the flag gates)
        metrics_out = os.path.join(
            env.dirs.outputs(), "placebo", task_id, "single", "0",
            "metrics.out",
        )
        assert os.path.getsize(metrics_out) > 0


class TestTasksListing:
    def test_columns_and_date_filters(self, tg_home, capsys):
        """`tg tasks` prints the reference's column order (ID / DATE /
        PLAN:CASE / DURATION / STATE / TYPE + outcome, tasks.go:50-54)
        and supports date-range filters over the archived store."""
        main(["plan", "import", "--from", os.path.join(PLANS, "placebo")])
        capsys.readouterr()
        assert main(
            [
                "run", "single", "placebo:ok",
                "--builder", "exec:py", "--runner", "local:exec", "-i", "1",
            ]
        ) == 0
        capsys.readouterr()

        assert main(["tasks"]) == 0
        out = capsys.readouterr().out
        line = [ln for ln in out.splitlines() if "placebo:ok" in ln][0]
        assert "complete" in line and "success" in line
        assert "s  " in line  # duration column
        import re

        assert re.search(r"\d{4}-\d{2}-\d{2} \d{2}:\d{2}:\d{2}", line)

        # --after tomorrow → nothing; --after yesterday → our task
        import datetime

        today = datetime.date.today()
        tomorrow = (today + datetime.timedelta(days=1)).isoformat()
        yesterday = (today - datetime.timedelta(days=1)).isoformat()
        assert main(["tasks", "--after", tomorrow]) == 0
        assert "placebo:ok" not in capsys.readouterr().out
        assert main(["tasks", "--after", yesterday]) == 0
        assert "placebo:ok" in capsys.readouterr().out
        assert main(["tasks", "--before", yesterday]) == 0
        assert "placebo:ok" not in capsys.readouterr().out

    def test_bad_date_errors(self, tg_home, capsys):
        assert main(["tasks", "--after", "not-a-date"]) == 1
        assert "cannot parse time" in capsys.readouterr().err


class TestBuildPurge:
    def test_purge_removes_plan_artifacts(self, tg_home, capsys):
        """`tg build purge -b exec:py -p placebo` removes the builder's
        cached snapshots for that plan and leaves other plans' artifacts
        alone (build.go:91-110)."""
        from testground_tpu.config import EnvConfig

        main(["plan", "import", "--from", os.path.join(PLANS, "placebo")])
        main(["plan", "import", "--from", os.path.join(PLANS, "example")])
        capsys.readouterr()
        assert main(["build", "single", "placebo", "--builder", "exec:py"]) == 0
        assert main(["build", "single", "example", "--builder", "exec:py"]) == 0
        capsys.readouterr()

        work = EnvConfig.load().dirs.work()
        before = os.listdir(work)
        assert any("placebo" in d for d in before)
        assert any("example" in d for d in before)

        assert main(["build", "purge", "-b", "exec:py", "-p", "placebo"]) == 0
        assert "purged exec:py cache" in capsys.readouterr().out
        after = os.listdir(work)
        assert not any("exec-py--placebo" in d for d in after)
        assert any("example" in d for d in after)

    def test_purge_unknown_builder_errors(self, tg_home, capsys):
        assert main(["build", "purge", "-b", "nope:x", "-p", "p"]) == 1
        assert "unknown builder" in capsys.readouterr().err

    def test_purge_does_not_touch_name_extending_plans(
        self, tg_home, tmp_path, capsys
    ):
        """Purging plan 'net' must not claim a plan named 'net-v2'
        (exact-id matching, not a bare prefix). Manifest names are the
        canonical plan identity (prepare_for_build), so the fixtures
        carry distinct manifests."""
        from testground_tpu.config import EnvConfig

        for name in ("net", "net-v2"):
            plan = tmp_path / name
            plan.mkdir()
            with open(os.path.join(PLANS, "placebo", "main.py")) as f:
                (plan / "main.py").write_text(f.read())
            (plan / "manifest.toml").write_text(
                f'name = "{name}"\n\n[defaults]\nbuilder = "exec:py"\n'
                'runner = "local:exec"\n\n[builders."exec:py"]\n'
                'enabled = true\n\n[runners."local:exec"]\nenabled = true\n'
                '\n[[testcases]]\nname = "ok"\n'
                "instances = { min = 1, max = 10, default = 1 }\n"
            )
            main(["plan", "import", "--from", str(plan)])
            capsys.readouterr()
            assert main(["build", "single", name, "--builder", "exec:py"]) == 0
        capsys.readouterr()

        work = EnvConfig.load().dirs.work()
        assert any(d.startswith("exec-py--net-v2-") for d in os.listdir(work))
        assert main(["build", "purge", "-b", "exec:py", "-p", "net"]) == 0
        after = os.listdir(work)
        # net's snapshot gone, net-v2's untouched
        assert not any(
            d.startswith("exec-py--net-") and not d.startswith("exec-py--net-v2-")
            for d in after
        )
        assert any(d.startswith("exec-py--net-v2-") for d in after)


class TestCollectVerb:
    def test_collect_writes_tgz(self, tg_home, tmp_path, capsys):
        """`tg collect <run-id> --runner X -o file` downloads the outputs
        archive (collect.go → POST /outputs; layout common.go:42-116)."""
        import tarfile

        main(["plan", "import", "--from", os.path.join(PLANS, "placebo")])
        capsys.readouterr()
        assert main(
            [
                "run", "single", "placebo:ok",
                "--builder", "exec:py", "--runner", "local:exec", "-i", "2",
            ]
        ) == 0
        out = capsys.readouterr().out
        task_id = out.split("run is queued with ID:")[1].split()[0]

        dest = tmp_path / "outs.tgz"
        assert main(
            ["collect", task_id, "--runner", "local:exec", "-o", str(dest)]
        ) == 0
        with tarfile.open(dest, mode="r:gz") as tar:
            names = tar.getnames()
        assert f"{task_id}/single/0/run.out" in names
        assert f"{task_id}/single/1/run.out" in names
