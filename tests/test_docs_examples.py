"""The worked examples in docs/WRITING_PLANS.md must actually run: the
code blocks are extracted verbatim, written into a plan directory, and
executed through the real engine on both substrates."""

import os
import re

import pytest

from testground_tpu.cli.main import main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GUIDE = os.path.join(REPO_ROOT, "docs", "WRITING_PLANS.md")


def _blocks():
    with open(GUIDE) as f:
        text = f.read()
    out = {}
    for lang, body in re.findall(r"```(python|toml)\n(.*?)```", text, re.S):
        # first line comment names the file for python blocks
        first = body.splitlines()[0].strip()
        if lang == "python" and first.startswith("#"):
            out[first.lstrip("# ").strip()] = body
        elif lang == "toml" and body.lstrip().startswith('name = "ring"'):
            out["manifest.toml"] = body
    return out


@pytest.fixture()
def ring_plan(tg_home, tmp_path):
    blocks = _blocks()
    assert set(blocks) >= {"main.py", "sim.py", "manifest.toml"}, blocks.keys()
    plan = tmp_path / "ring"
    plan.mkdir()
    (plan / "sim.py").write_text(blocks["sim.py"])
    (plan / "manifest.toml").write_text(blocks["manifest.toml"])
    # the guide's exec example is a generic barrier demo under testcase
    # "ok"; the manifest declares "ring" — expose both for the exec run
    (plan / "main.py").write_text(
        blocks["main.py"].replace('{"ok": ok}', '{"ok": ok, "ring": ok}')
    )
    assert main(["plan", "import", "--from", str(plan)]) == 0
    return plan


class TestGuideExamples:
    def test_sim_edition_runs(self, ring_plan, capsys):
        capsys.readouterr()
        rc = main(
            [
                "run", "single", "ring:ring",
                "--builder", "sim:plan", "--runner", "sim:jax",
                "-i", "8",
                # bound the budget so a broken example fails in seconds
                "--run-cfg", "max_ticks=512", "--run-cfg", "chunk=32",
            ]
        )
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "outcome: success" in out

    def test_exec_edition_runs(self, ring_plan, capsys):
        capsys.readouterr()
        rc = main(
            [
                "run", "single", "ring:ring",
                "--builder", "exec:py", "--runner", "local:exec",
                "-i", "3",
            ]
        )
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "outcome: success" in out
