"""Tick phase attribution plane (docs/OBSERVABILITY.md "Phase
attribution"; sim/phases.py).

Pins the acceptance contract: each compiled-in tick phase lowers
standalone and its cost rows sum to the whole-program chunk cost within
the EXPLICIT residual row (both transport backends — pallas in
interpret mode on CPU); the attribution is pure out-of-line bookkeeping
(the run's chunk program is jaxpr-identical before and after building
the ledger, and the named_scope annotations change no jaxpr); the
measured calibration stamps every phase; the journal/jsonl/Prometheus/
artifact surfaces agree.
"""

import json
import os

import pytest

from testground_tpu.api import RunGroup
from testground_tpu.sim.engine import SimProgram, build_groups
from testground_tpu.sim.executor import (
    instantiate_testcase,
    load_sim_testcases,
)
from testground_tpu.sim.phases import (
    PHASES_FILE,
    TICK_PHASES,
    build_phase_ledger,
    phase_rows,
    write_phase_rows,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


def make_groups(*counts, params=None):
    return build_groups(
        [
            RunGroup(id=f"g{i}", instances=c, parameters=dict(params or {}))
            for i, c in enumerate(counts)
        ]
    )


def make_prog(case="ping-pong", plan="network", n=4, params=None, **kw):
    factory = load_sim_testcases(os.path.join(PLANS, plan))[case]
    groups = make_groups(n, params=params)
    tc = instantiate_testcase(factory, groups, tick_ms=1.0)
    return SimProgram(tc, groups, chunk=8, **kw)


def assert_conserves(block):
    """Σ phases + residual == whole_per_tick, for every cost field the
    whole-program analysis produced (the block rounds to 3 decimals)."""
    whole = block["whole_per_tick"]
    assert whole, "no whole-program cost analysis on this backend"
    for key, total in whole.items():
        s = sum(float(r.get(key, 0.0) or 0.0) for r in block["phases"])
        assert (
            abs(s + block["residual"][key] - total)
            <= 0.02 + 1e-6 * abs(total)
        ), (key, s, block["residual"][key], total)


# --------------------------------------------------------- static ledger


class TestPhaseLedger:
    def test_coverage_and_residual_conservation_xla(self):
        """Telemetry program on the default backend: every compiled-in
        phase contributes a cost row, in dataflow order, the rows sum
        to the whole-program chunk cost within the explicit residual,
        and the measured calibration stamps every phase (one program
        build serves both assertions — tier-1 budget)."""
        prog = make_prog(telemetry=True)
        block = build_phase_ledger(prog, measure=2)
        names = [r["phase"] for r in block["phases"]]
        assert names == [
            "deliver",
            "lat_hist",
            "step",
            "sync",
            "net_commit",
            "telemetry",
        ]
        assert set(names) <= set(TICK_PHASES)
        assert block["transport"] == "xla"
        assert block["chunk"] == 8 and block["instances"] == 4
        assert_conserves(block)
        # fractions accompany every row where the whole-program analysis
        # produced the denominator
        for r in block["phases"]:
            if "bytes_accessed" in r and block["whole_per_tick"].get(
                "bytes_accessed"
            ):
                assert "bytes_frac" in r
        # measured calibration: every phase timed, reps recorded
        for r in block["phases"]:
            assert r.get("measured_ms", 0) > 0, r
            assert r.get("measured_reps") == 2

    def test_pallas_backend_ledger_interpret_mode(self):
        """transport=pallas (interpret mode on CPU) attributes the same
        phase set minus the telemetry-gated rows, tagged with its
        backend, and conserves against ITS whole-program cost."""
        prog = make_prog(
            case="pingpong-sustained",
            params={
                "duration_ticks": "64",
                "latency_ms": "4",
                "latency2_ms": "2",
                "reshape_every": "1000",
            },
            transport="pallas",
        )
        block = build_phase_ledger(prog)
        names = [r["phase"] for r in block["phases"]]
        assert names == ["deliver", "step", "sync", "net_commit"]
        assert block["transport"] == "pallas"
        assert_conserves(block)

    def test_faults_phase_present_when_scheduled(self):
        from testground_tpu.sim.faults import build_fault_schedule

        factory = load_sim_testcases(os.path.join(PLANS, "network"))[
            "ping-pong"
        ]
        groups = make_groups(4)
        tc = instantiate_testcase(factory, groups, tick_ms=1.0)
        sched = build_fault_schedule(
            groups,
            {"g0": [{"kind": "crash", "start_ms": 3, "instances": "0:1"}]},
            1.0,
        )
        prog = SimProgram(tc, groups, chunk=8, faults=sched)
        block = build_phase_ledger(prog)
        names = [r["phase"] for r in block["phases"]]
        assert names[0] == "faults"
        assert_conserves(block)

    def test_ledger_leaves_the_program_untouched(self):
        """The attribution is out-of-line bookkeeping: the run's chunk
        program traces the identical jaxpr before and after building
        the ledger (the zero-overhead contract, extended to this
        plane)."""
        import jax

        prog = make_prog(telemetry=True)
        carry = jax.eval_shape(lambda: prog.init_carry(0))
        before = str(jax.make_jaxpr(prog._chunk_step)(carry))
        build_phase_ledger(prog)
        assert str(jax.make_jaxpr(prog._chunk_step)(carry)) == before

    def test_whole_cost_reuse_normalizes_per_tick(self):
        """A pre-harvested whole-program block (the perf ledger's
        compile analysis — per CHUNK) is reused instead of recompiling,
        normalized by the chunk length."""
        prog = make_prog()
        block = build_phase_ledger(
            prog, whole={"flops": 800.0, "bytes_accessed": 1600.0}
        )
        assert block["whole_per_tick"]["flops"] == pytest.approx(100.0)
        assert block["whole_per_tick"]["bytes_accessed"] == pytest.approx(
            200.0
        )
        assert_conserves(block)


# -------------------------------------------------------- named scopes


class TestNamedScopes:
    def test_tick_traces_under_phase_scopes(self, monkeypatch):
        """Every tick phase executes under jax.named_scope("tg.<phase>")
        — the XProf/Perfetto attribution contract. Recorded by
        intercepting named_scope during a trace of the chunk program."""
        import contextlib

        import jax

        seen = []
        real = jax.named_scope

        def recorder(name):
            seen.append(name)
            return (
                real(name)
                if isinstance(name, str)
                else contextlib.nullcontext()
            )

        monkeypatch.setattr(jax, "named_scope", recorder)
        prog = make_prog(telemetry=True)
        jax.make_jaxpr(prog._chunk_step)(
            jax.eval_shape(lambda: prog.init_carry(0))
        )
        for phase in (
            "tg.faults",
            "tg.deliver",
            "tg.lat_hist",
            "tg.step",
            "tg.net_commit",
            "tg.sync",
            "tg.trace",
            "tg.telemetry",
        ):
            assert phase in seen, (phase, sorted(set(seen)))

    def test_default_program_jaxpr_matches_a_scopeless_trace(self):
        """named_scope is name-stack metadata only: stripping the scopes
        changes NO jaxpr — the pinned zero-overhead contract holds with
        the annotations compiled in."""
        import contextlib
        from unittest import mock

        import jax

        prog = make_prog()
        carry = jax.eval_shape(lambda: prog.init_carry(0))
        with_scopes = str(jax.make_jaxpr(prog._chunk_step)(carry))
        with mock.patch.object(
            jax, "named_scope", lambda name: contextlib.nullcontext()
        ):
            without = str(jax.make_jaxpr(prog._chunk_step)(carry))
        assert with_scopes == without


# ------------------------------------------------------------ row shapes


class TestPhaseRows:
    BLOCK = {
        "transport": "pallas",
        "chunk": 8,
        "phases": [
            {"phase": "deliver", "flops": 10.0, "bytes_accessed": 100.0},
            {"phase": "net_commit", "flops": 30.0},
        ],
        "whole_per_tick": {"flops": 50.0, "bytes_accessed": 120.0},
        "residual": {"flops": 10.0, "bytes_accessed": 20.0},
    }

    def test_rows_include_residual_and_total(self):
        rows = phase_rows(self.BLOCK)
        assert [r["phase"] for r in rows] == [
            "deliver",
            "net_commit",
            "residual",
            "total",
        ]
        assert all(r["transport"] == "pallas" for r in rows)
        assert rows[-1]["flops"] == 50.0

    def test_tolerates_foreign_shapes(self):
        assert phase_rows({}) == []
        assert phase_rows(None) == []
        assert phase_rows({"phases": [{"nope": 1}, "junk"]}) == []

    def test_write_phase_rows_roundtrip(self, tmp_path):
        path = os.path.join(tmp_path, PHASES_FILE)
        n = write_phase_rows(path, {"run": "r1", "plan": "p"}, self.BLOCK)
        assert n == 4
        rows = [json.loads(ln) for ln in open(path)]
        assert len(rows) == 4
        assert rows[0]["run"] == "r1" and rows[0]["phase"] == "deliver"
        assert rows[-2]["phase"] == "residual"

    def test_render_phase_table(self):
        from testground_tpu.runners.pretty import render_phase_table

        table = render_phase_table({"phases": self.BLOCK})
        assert "net_commit" in table and "residual" in table
        assert "transport=pallas" in table
        # absent block degrades to a hint, never a crash
        hint = render_phase_table({"sim": {}})
        assert "phases=true" in hint


# ------------------------------------------------------------ prometheus


class TestPrometheusPhases:
    def _task(self, phases):
        from testground_tpu.engine.task import (
            DatedState,
            State,
            Task,
            TaskType,
        )

        return Task(
            id="t1",
            type=TaskType.RUN,
            plan="network",
            case="ping-pong",
            states=[
                DatedState(state=State.SCHEDULED, created=1.0),
                DatedState(state=State.COMPLETE, created=2.0),
            ],
            result={
                "outcome": "success",
                "journal": {"sim": {"ticks": 16, "phases": phases}},
            },
        )

    def test_phase_gauges_valid_and_labeled(self):
        import re

        from testground_tpu.metrics.prometheus import render_prometheus

        block = dict(TestPhaseRows.BLOCK)
        block["phases"] = [
            {**block["phases"][0], "measured_ms": 0.25},
            block["phases"][1],
        ]
        text = render_prometheus([self._task(block)])
        line_re = re.compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? "
            r"-?[0-9.e+-]+(\.[0-9]+)?$"
        )
        for line in text.strip().splitlines():
            if line.startswith("# "):
                continue
            assert line_re.match(line), line
        assert 'tg_phase_flops{task="t1"' in text
        assert 'phase="deliver"' in text
        assert 'phase="residual"' in text and 'phase="total"' in text
        assert 'transport="pallas"' in text
        assert "tg_phase_measured_ms{" in text
        assert text.count("# TYPE tg_phase_flops") == 1

    def test_absent_block_adds_no_phase_families(self):
        from testground_tpu.metrics.prometheus import render_prometheus

        text = render_prometheus([self._task({})])
        assert "tg_phase_" not in text


# ------------------------------------------------ payload + stream + artifact


class TestSurfaces:
    def test_perf_payload_surfaces_phases_top_level(self):
        from testground_tpu.engine.task import (
            DatedState,
            State,
            Task,
            TaskType,
        )

        block = {"phases": [{"phase": "deliver"}], "transport": "xla"}
        t = Task(
            id="t1",
            type=TaskType.RUN,
            plan="p",
            case="c",
            states=[DatedState(state=State.COMPLETE, created=1.0)],
            result={"journal": {"sim": {"ticks": 8, "phases": block}}},
        )
        payload = t.perf_payload()
        assert payload["phases"] == block
        assert "phases" not in payload["sim"]  # surfaced, not duplicated

    def test_stream_family_registered(self):
        from testground_tpu.engine.stream import STREAM_FAMILIES

        assert ("phases", PHASES_FILE) in STREAM_FAMILIES

    def test_artifact_whitelist(self):
        from testground_tpu.daemon.server import _Handler

        rp = _Handler._artifact_relpath
        assert rp(PHASES_FILE) == PHASES_FILE
        ok = "profiles/plugins/profile/sess_1/host.xplane.pb"
        assert rp(ok) == os.path.join(*ok.split("/"))
        # traversal, wrong depth, wrong suffix, absolute: all refused
        assert rp("profiles/plugins/profile/../x/host.xplane.pb") is None
        assert rp("profiles/plugins/profile/host.xplane.pb") is None
        assert rp("profiles/plugins/profile/a/b/host.xplane.pb") is None
        assert rp("profiles/plugins/profile/sess/evil.pstats") is None
        assert rp("plugins/profile/sess/host.xplane.pb") is None
        assert rp("/etc/passwd") is None


# ------------------------------------------------------- chunked profiler


class TestChunkedProfiler:
    def _patched(self, monkeypatch):
        import jax

        from testground_tpu.sim import executor as ex

        calls = []
        monkeypatch.setattr(
            jax.profiler, "start_trace", lambda d: calls.append(("start", d))
        )
        monkeypatch.setattr(
            jax.profiler, "stop_trace", lambda: calls.append(("stop",))
        )
        return ex._ChunkedProfiler, calls

    def test_window_starts_after_warmup_and_stops_after_n(self, monkeypatch):
        cls, calls = self._patched(monkeypatch)
        p = cls("/tmp/prof", chunks=2)
        p.on_chunk(16)  # warmup chunk done → trace starts here
        assert calls == [("start", "/tmp/prof")]
        p.on_chunk(32)
        assert p.captured == 1 and not p.done
        p.on_chunk(48)  # second captured chunk → stop
        assert calls[-1] == ("stop",)
        p.on_chunk(64)  # past the window: no-op
        assert len(calls) == 2
        assert p.journal() == {
            "dir": "profiles",
            "mode": "chunks",
            "chunks": 2,
            "from_tick": 16,
            "to_tick": 48,
        }

    def test_close_stops_an_open_capture(self, monkeypatch):
        """A run finishing (or failing) inside the window still closes
        the trace — an unterminated session would poison the process."""
        cls, calls = self._patched(monkeypatch)
        p = cls("/tmp/prof", chunks=8)
        p.on_chunk(16)
        p.on_chunk(32)
        p.close()
        assert calls[-1] == ("stop",)
        p.close()  # idempotent
        assert calls.count(("stop",)) == 1

    def test_profiler_failure_disables_capture_not_the_run(
        self, monkeypatch
    ):
        import jax

        from testground_tpu.sim import executor as ex

        def boom(d):
            raise RuntimeError("profiler unavailable")

        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        p = ex._ChunkedProfiler("/tmp/prof", chunks=1)
        p.on_chunk(16)  # swallowed
        p.on_chunk(32)
        p.close()
        assert p.done and not p.started


# ------------------------------------------------------------------ e2e
# (tg_home fixture from tests/conftest.py: isolated $TESTGROUND_HOME)


class TestExecutorE2E:
    def _run(self, run_params, engine=None, env=None):
        from tests.test_sim_runner import run_sim
        from testground_tpu.builders.sim_plan import SimPlanBuilder
        from testground_tpu.engine import Engine, EngineConfig, Outcome
        from testground_tpu.sim.runner import SimJaxRunner

        from testground_tpu.config import EnvConfig

        own = engine is None
        if own:
            env = EnvConfig.load()
            engine = Engine(
                EngineConfig(
                    env=env,
                    builders=[SimPlanBuilder()],
                    runners=[SimJaxRunner()],
                )
            )
            engine.start_workers()
        try:
            task = run_sim(
                engine,
                "network",
                "ping-pong",
                instances=2,
                run_params=run_params,
            )
        finally:
            if own:
                engine.stop()
        assert task.outcome() == Outcome.SUCCESS, task.error
        return env, engine, task

    def test_journal_and_jsonl_agree_and_off_by_default(self, tg_home):
        """phases=true journals sim.phases and mirrors it row for row
        to sim_phases.jsonl (phases + residual + total), conserving the
        cost identity end-to-end; without the knob the run stays
        phase-free (one engine serves both runs — tier-1 budget)."""
        from testground_tpu.builders.sim_plan import SimPlanBuilder
        from testground_tpu.config import EnvConfig
        from testground_tpu.engine import Engine, EngineConfig
        from testground_tpu.sim.runner import SimJaxRunner

        env = EnvConfig.load()
        engine = Engine(
            EngineConfig(
                env=env,
                builders=[SimPlanBuilder()],
                runners=[SimJaxRunner()],
            )
        )
        engine.start_workers()
        try:
            _, _, task = self._run(
                {"chunk": 16, "phases": True, "telemetry": True},
                engine=engine,
                env=env,
            )
            _, _, task_off = self._run(
                {"chunk": 16}, engine=engine, env=env
            )
        finally:
            engine.stop()
        block = task.result["journal"]["sim"]["phases"]
        assert_conserves(block)
        names = [r["phase"] for r in block["phases"]]
        assert "net_commit" in names and "telemetry" in names
        path = os.path.join(
            env.dirs.outputs(), "network", task.id, PHASES_FILE
        )
        rows = [json.loads(ln) for ln in open(path)]
        assert [r["phase"] for r in rows] == names + ["residual", "total"]
        assert block["series"] == {"rows": len(rows), "file": PHASES_FILE}
        # static-only run: no measured column anywhere
        assert not any("measured_ms" in r for r in block["phases"])
        # off by default: no journal block, no jsonl
        assert "phases" not in task_off.result["journal"]["sim"]
        assert not os.path.isfile(
            os.path.join(
                env.dirs.outputs(), "network", task_off.id, PHASES_FILE
            )
        )

    @pytest.mark.slow  # ~29s: jax.profiler start/stop + xplane
    # serialization put it past the tier-1 ~20s ceiling (the whole-run
    # profile capture test is slow-marked for the same reason); the
    # window logic itself is covered by the fast TestChunkedProfiler
    def test_bounded_profile_capture(self, tg_home):
        """profile_chunks=N captures only the configured chunk window
        after warmup (journaled), instead of wrapping the whole run in
        jax.profiler.trace."""
        env, _, task = self._run(
            {"chunk": 16, "profile": True, "profile_chunks": 1},
        )
        prof = task.result["journal"]["profile"]
        assert prof["mode"] == "chunks"
        assert prof["chunks"] == 1
        # window: starts at the first chunk boundary, spans one chunk
        assert prof["from_tick"] == 16 and prof["to_tick"] == 32
        cap_dir = os.path.join(
            env.dirs.outputs(),
            "network",
            task.id,
            "profiles",
            "plugins",
            "profile",
        )
        assert os.path.isdir(cap_dir)
        captures = [
            os.path.join(dp, f)
            for dp, _, fs in os.walk(cap_dir)
            for f in fs
            if f.endswith(".xplane.pb")
        ]
        assert captures, "no xplane capture written"
        # every capture file is fetchable through the artifact whitelist
        from testground_tpu.daemon.server import _Handler

        run_dir = os.path.join(env.dirs.outputs(), "network", task.id)
        for p in captures:
            rel = os.path.relpath(p, run_dir).replace(os.sep, "/")
            assert _Handler._artifact_relpath(rel) is not None, rel
