"""The shipped Grafana dashboard (the analog of the reference's
``plans/benchmarks/grafana-dashboard/storm.json``) must stay in sync with
the measurement names the benchmark plans actually emit through the
InfluxDB mirror."""

import json
import os
import re

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DASH = os.path.join(
    REPO_ROOT, "plans", "benchmarks", "grafana-dashboard", "dashboard.json"
)


def _emitted_measurements():
    """Measurement names every benchmarks testcase can produce, as the
    influx mirror names them (results.<plan>-<case>.<metric>)."""
    import numpy as np

    from testground_tpu.metrics.viewer import measurement_name
    from plans.benchmarks.sim import SUBTREE_SIZES  # noqa: F401

    names = set()
    # static names per testcase (mirror of each collect_metrics)
    per_case = {
        "barrier": [
            f"barrier_time_{p}_percent" for p in (20, 40, 60, 80, 100)
        ],
        "netinit": ["time_to_network_init_ticks"],
        "netlinkshape": [
            "time_to_shape_network_ticks",
            "shaped_latency_ticks",
        ],
        "subtree": [
            f"subtree_time_{s}_bytes_{d}_ticks"
            for s in SUBTREE_SIZES
            for d in ("publish", "receive")
        ],
        "storm": ["storm.bytes_sent", "storm.bytes_read"],
        "pingpong-flood": ["flood.rounds"],
        # startup has no collect_metrics: its measurement is finished_at
    }
    for case, metrics in per_case.items():
        for m in metrics:
            names.add(measurement_name("benchmarks", case, m))
    assert np is not None
    return names


def test_dashboard_is_valid_json_with_known_measurements():
    with open(DASH) as f:
        dash = json.load(f)
    assert dash["panels"], "dashboard has no panels"
    emitted = _emitted_measurements()
    queried = set()
    for panel in dash["panels"]:
        for target in panel.get("targets", []):
            q = target.get("query", "")
            for m in re.findall(r'FROM\s+"([^"]+)"', q):
                queried.add(m)
    assert queried, "no influx queries found in dashboard"
    unknown = queried - emitted
    assert not unknown, f"dashboard queries unknown measurements: {unknown}"
