"""Cross-host sync plane (docs/CROSSHOST.md): the acceptance pin for
ISSUE 10 — a two-"host" ping-pong with instances split across two
process groups as hosts, the second one ENGINE-LESS (separate
$TESTGROUND_HOME, joining purely by sync-service address), green on both
sync backends; plus the runner's external-service mode and the
bind/advertise address logic."""

import os
import subprocess
import sys
import time

import pytest

from testground_tpu.sdk.runparams import RunParams
from testground_tpu.sync import (
    SyncClient,
    SyncRetry,
    advertise_host,
    parse_hostport,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


@pytest.fixture(scope="session")
def native_bin(tmp_path_factory):
    from testground_tpu.native import build_syncsvc, native_available

    if not native_available():
        pytest.skip("no C++ toolchain")
    return build_syncsvc(str(tmp_path_factory.mktemp("syncsvc-bin")))


def _spawn_service(backend, native_bin, host="127.0.0.1", idle=5.0):
    """External standalone sync service of either backend; returns
    (proc, dial_host, port)."""
    if backend == "python":
        code = (
            "from testground_tpu.sync.server import _main; "
            f"_main(['--host', '{host}', '--port', '0', "
            f"'--idle-timeout', '{idle}'])"
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            env={**os.environ, "PYTHONPATH": REPO_ROOT},
        )
        parts = proc.stdout.readline().split()
        assert parts and parts[0] == "LISTENING", parts
        port = int(parts[2])
    else:
        argv = [native_bin, "--port", "0", "--host", host,
                "--idle-timeout", str(idle)]
        proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True
        )
        parts = proc.stdout.readline().split()
        assert parts and parts[0] == "LISTENING", parts
        port = int(parts[1])
    return proc, advertise_host(host), port


@pytest.fixture(params=["python", "native"])
def external_service(request, tmp_path):
    native = None
    if request.param == "native":
        native = request.getfixturevalue("native_bin")
    # wildcard bind: the service is a network citizen; instances dial the
    # machine's advertised (non-wildcard) address
    proc, host, port = _spawn_service(request.param, native, host="0.0.0.0")
    yield host, port
    proc.kill()
    proc.wait(timeout=10)


def _spawn_engineless_instance(
    group: str,
    instance_seq: int,
    run_id: str,
    sync_host: str,
    sync_port: int,
    home: str,
    total: int = 2,
):
    """One instance process driven purely by the RunParams env contract —
    no engine, no runner: the 'second host' of a cross-host run (the
    scheduler on that host injected the same run id + sync address, the
    ``cluster_k8s.go:302`` pattern)."""
    out_dir = os.path.join(home, "outputs", group, str(instance_seq))
    tmp_dir = os.path.join(home, "tmp", group, str(instance_seq))
    params = RunParams(
        test_plan="network",
        test_case="ping-pong",
        test_run=run_id,
        test_instance_count=total,
        test_group_id=group,
        test_group_instance_count=1,
        test_outputs_path=out_dir,
        test_temp_path=tmp_dir,
        test_instance_seq=instance_seq,
        test_group_seq=0,
        sync_service_host=sync_host,
        sync_service_port=sync_port,
        sync_connect_timeout=2.0,
        sync_retry_attempts=20,
        sync_retry_deadline=30.0,
        sync_heartbeat=0.5,
    )
    env = {**os.environ, **params.to_env()}
    env["PYTHONPATH"] = REPO_ROOT
    artifact = os.path.join(PLANS, "network", "main.py")
    return subprocess.Popen(
        [sys.executable, artifact],
        env=env,
        cwd=os.path.dirname(artifact),
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )


class TestTwoHostPingPong:
    def test_split_instances_across_two_hosts(
        self, external_service, tmp_path
    ):
        """The acceptance pin: one run, two instances, each in its own
        process group with its own $TESTGROUND_HOME ("hosts"), meeting
        only through the network-reachable sync service — address
        exchange via pubsub, rendezvous via signal_and_wait, then real
        TCP ping-pong rounds. Both backends (fixture param)."""
        host, port = external_service
        run_id = f"xhost-{int(time.time() * 1000) % 10**9:09d}"
        homes = [str(tmp_path / "hostA"), str(tmp_path / "hostB")]
        procs = [
            _spawn_engineless_instance(
                f"host{chr(65 + i)}", i, run_id, host, port, homes[i]
            )
            for i in range(2)
        ]
        outs = []
        for p in procs:
            try:
                out, err = p.communicate(timeout=60)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            outs.append((p.returncode, out, err))
        for rc, out, err in outs:
            assert rc == 0, f"instance failed rc={rc}\n{out}\n{err}"
        # the dialer measured real RTTs; both recorded success events
        assert any('"success"' in out for _, out, _ in outs)

    def test_second_host_sees_first_hosts_barriers_and_pubsub(
        self, external_service
    ):
        """An engine-less joiner (bare SyncClient by address) observes
        host A's signals, meets its barrier, and reads its topic — the
        primitives themselves, without a plan around them."""
        host, port = external_service
        ns = f"run:join-{port}:"
        a = SyncClient(host, port, namespace=ns, retry=SyncRetry(heartbeat_secs=0.5))
        b = SyncClient(host, port, namespace=ns, retry=SyncRetry(heartbeat_secs=0.5))
        try:
            a.publish("topic", {"from": "hostA"})
            assert next(b.subscribe("topic", timeout=10)) == {"from": "hostA"}
            import threading

            seqs: list = []
            t = threading.Thread(
                target=lambda: seqs.append(a.signal_and_wait("gate", 2, timeout=15)),
                daemon=True,
            )
            t.start()
            time.sleep(0.2)
            seqs.append(b.signal_and_wait("gate", 2, timeout=15))
            t.join(timeout=15)
            assert sorted(seqs) == [1, 2]
        finally:
            a.close()
            b.close()


class TestRunnerExternalServiceMode:
    @pytest.fixture()
    def engine(self, tg_home):
        from testground_tpu.builders.exec_py import ExecPyBuilder
        from testground_tpu.config import EnvConfig
        from testground_tpu.engine import Engine, EngineConfig
        from testground_tpu.runners.local_exec import LocalExecRunner

        env = EnvConfig.load()
        e = Engine(
            EngineConfig(
                env=env, builders=[ExecPyBuilder()], runners=[LocalExecRunner()]
            )
        )
        e.start_workers()
        yield e
        e.stop()

    def _run(self, engine, plan, case, instances, run_config, timeout=90):
        from testground_tpu.api import (
            Composition,
            Global,
            Group,
            Instances,
            TestPlanManifest,
            generate_default_run,
        )
        from testground_tpu.engine import State

        comp = generate_default_run(
            Composition(
                global_=Global(
                    plan=plan,
                    case=case,
                    builder="exec:py",
                    runner="local:exec",
                    run_config=dict(run_config),
                ),
                groups=[Group(id="all", instances=Instances(count=instances))],
            )
        )
        manifest = TestPlanManifest.load_file(
            os.path.join(PLANS, plan, "manifest.toml")
        )
        tid = engine.queue_run(
            comp, manifest, sources_dir=os.path.join(PLANS, plan)
        )
        deadline = time.time() + timeout
        while time.time() < deadline:
            t = engine.get_task(tid)
            if t is not None and t.state().state in (
                State.COMPLETE,
                State.CANCELED,
            ):
                return t
            time.sleep(0.05)
        raise TimeoutError(f"task {tid} did not finish")

    def test_run_joins_external_service_and_does_not_stop_it(
        self, engine, external_service
    ):
        """A runner configured with sync_service_address starts no server
        of its own, completes green through the shared plane, and leaves
        the external service running (its owner stops it)."""
        from testground_tpu.engine import Outcome

        host, port = external_service
        t = self._run(
            engine,
            "placebo",
            "ok",
            2,
            {"sync_service_address": f"{host}:{port}"},
        )
        assert t.outcome() == Outcome.SUCCESS
        assert t.result["outcomes"]["all"] == {"total": 2, "ok": 2}
        # still alive and answering after the run tore down
        probe = SyncClient(host, port, retry=SyncRetry(heartbeat_secs=0))
        try:
            assert probe.ping(timeout=5)
        finally:
            probe.close()

    def test_unreachable_external_service_fails_fast_and_readably(
        self, engine
    ):
        import socket

        from testground_tpu.engine import Outcome

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        t = self._run(
            engine,
            "placebo",
            "ok",
            1,
            {"sync_service_address": f"127.0.0.1:{port}"},
        )
        assert t.outcome() == Outcome.FAILURE


class TestAddressing:
    def test_parse_hostport(self):
        assert parse_hostport("10.0.0.5:9042") == ("10.0.0.5", 9042)
        assert parse_hostport("somehost", default_port=7) == ("somehost", 7)
        with pytest.raises(ValueError):
            parse_hostport(":9042")
        with pytest.raises(ValueError):
            parse_hostport("h:not-a-port")
        with pytest.raises(ValueError):
            parse_hostport("h:70000")

    def test_advertise_host(self):
        assert advertise_host("192.168.1.7") == "192.168.1.7"
        assert advertise_host("0.0.0.0", explicit="10.1.2.3") == "10.1.2.3"
        resolved = advertise_host("0.0.0.0")
        assert resolved not in ("", "0.0.0.0", "::")

    def test_loopback_remains_the_default_bind(self):
        """The default runner config binds loopback — cross-host exposure
        is opt-in."""
        from testground_tpu.runners.local_exec import LocalExecConfig
        from testground_tpu.sync import SyncServiceServer

        assert LocalExecConfig().sync_bind_host == "127.0.0.1"
        srv = SyncServiceServer().start()
        try:
            assert srv.address[0] == "127.0.0.1"
        finally:
            srv.stop()
