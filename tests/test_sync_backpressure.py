"""Slow-reader backpressure + event-loop sharding (the 10k fan-in
server rewrite, docs/CROSSHOST.md "Server architecture").

The regression this pins: a subscribed client that STOPS READING while
a hot topic floods must not delay other peers' barrier releases past a
bound — on BOTH backends. In an event-loop server a stalled reader's
backlog is the one thing that can wedge everyone (the old thread-per-
connection design isolated it by accident); the bounded per-peer
outbound queues exist to kill exactly this shape: once the backlog
trips the bound the peer is shed (dropped + counted as an eviction) and
every other connection stays live and fast.

Plus a sharded-loop parity check: with connections spread across
multiple event loops, cross-shard barrier releases and pubsub fanout
must behave exactly like the single-loop default.
"""

import json
import socket
import threading
import time

import pytest

from testground_tpu.sync import SyncClient, SyncRetry, SyncServiceServer
from testground_tpu.sync.stats import fetch_sync_stats

# small outbound bound so the shed trips fast in a test (production
# default is 16 MiB; see SyncServiceServer.outq_limit / --max-wbuf)
OUTQ_BOUND = 65536


def _fast_retry():
    return SyncRetry(
        connect_timeout=2.0,
        attempts=2,
        deadline_secs=3.0,
        heartbeat_secs=0.0,
    )


@pytest.fixture(scope="session")
def native_bin(tmp_path_factory):
    from testground_tpu.native import build_syncsvc, native_available

    if not native_available():
        pytest.skip("no C++ toolchain for the native sync service")
    return build_syncsvc(str(tmp_path_factory.mktemp("syncsvc-bin")))


@pytest.fixture(params=["python", "native"])
def bounded_server(request):
    """A server of either backend with a tiny per-peer outbound bound;
    yields (address, backend)."""
    if request.param == "python":
        srv = SyncServiceServer(outq_limit=OUTQ_BOUND).start()
        yield srv.address, "python"
        srv.stop()
    else:
        from testground_tpu.native import NativeSyncService

        srv = NativeSyncService(
            request.getfixturevalue("native_bin"), max_wbuf=OUTQ_BOUND
        )
        yield srv.address, "native"
        srv.stop()


def _stalled_subscriber(host, port, topic):
    """A raw socket that subscribes and then never reads again — the
    SIGSTOPped/wedged-consumer shape. A tiny SO_RCVBUF keeps the kernel
    from absorbing the flood on the server's behalf."""
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 8192)
    s.connect((host, port))
    s.sendall(
        (json.dumps({"id": 1, "op": "subscribe", "topic": topic}) + "\n")
        .encode()
    )
    return s


class TestSlowReaderBackpressure:
    def test_stalled_subscriber_never_delays_barriers(self, bounded_server):
        (host, port), backend = bounded_server
        evict0 = (
            (fetch_sync_stats(host, port).get("conn") or {}).get(
                "evictions", 0
            )
        )
        stalled = _stalled_subscriber(host, port, "hot")
        publisher = SyncClient(host, port, retry=_fast_retry())
        a = SyncClient(host, port, namespace="bp:", retry=_fast_retry())
        b = SyncClient(host, port, namespace="bp:", retry=_fast_retry())
        # the kernel absorbs ~4 MiB (tcp_wmem autotuning) before the
        # server-side queue starts growing at all — the flood must
        # overrun that AND the 64 KiB bound to prove the shed
        payload = {"blob": "x" * 8192}
        worst_barrier = 0.0
        try:
            # flood the hot topic while measuring unrelated 2-party
            # barriers; each round must release promptly even while the
            # stalled reader's backlog grows toward the bound
            for round_ in range(10):
                for _ in range(60):
                    publisher.publish("hot", payload)
                got = {}

                def other(i=round_):
                    got["b"] = b.signal_and_wait(f"gate-{i}", 2, timeout=10)

                t = threading.Thread(target=other, daemon=True)
                t0 = time.monotonic()
                t.start()
                a.signal_and_wait(f"gate-{round_}", 2, timeout=10)
                t.join(timeout=10)
                wall = time.monotonic() - t0
                worst_barrier = max(worst_barrier, wall)
                assert got.get("b") in (1, 2)
                assert wall < 5.0, (
                    f"{backend}: barrier round {round_} took {wall:.1f}s "
                    "behind a stalled subscriber"
                )
            # the flood replicated ~5 MiB into a reader with an 8 KiB
            # receive window: past the kernel's autotuned send buffer
            # the server-side backlog trips the 64 KiB bound — the
            # server must have shed it, counted as an eviction
            deadline = time.monotonic() + 10
            evictions = 0
            while time.monotonic() < deadline:
                snap = fetch_sync_stats(host, port)
                evictions = (snap.get("conn") or {}).get("evictions", 0)
                if evictions > evict0:
                    break
                time.sleep(0.2)
            assert evictions > evict0, (
                f"{backend}: stalled subscriber was never shed "
                f"(evictions {evict0} -> {evictions})"
            )
            # healthy clients are untouched
            assert publisher.counter("nothing") == 0
            assert a.signal_entry("still-alive") == 1
        finally:
            stalled.close()
            publisher.close()
            a.close()
            b.close()

    def test_fast_subscriber_still_sees_the_flood(self, bounded_server):
        """The bound sheds READERS THAT STOPPED, not slow-but-live
        ones: a subscriber that keeps draining receives every entry."""
        (host, port), backend = bounded_server
        sub_client = SyncClient(host, port, retry=_fast_retry())
        publisher = SyncClient(host, port, retry=_fast_retry())
        try:
            entries = sub_client.subscribe("steady", timeout=15)
            for i in range(100):
                publisher.publish("steady", {"i": i})
            got = [next(entries)["i"] for _ in range(100)]
            assert got == list(range(100)), f"{backend}: lost entries"
        finally:
            sub_client.close()
            publisher.close()


class TestHostileLines:
    """Wire robustness of the event loops: a hostile or odd line must
    cost at most its own connection, never the loop."""

    @pytest.fixture(params=["python", "native"])
    def any_server(self, request):
        if request.param == "python":
            srv = SyncServiceServer().start()
            yield srv.address
            srv.stop()
        else:
            from testground_tpu.native import NativeSyncService

            srv = NativeSyncService(request.getfixturevalue("native_bin"))
            yield srv.address
            srv.stop()

    def test_non_dict_json_line_does_not_kill_the_loop(self, any_server):
        # regression: `5\n` parses as an int; the dispatch must answer
        # "malformed request" — an uncaught AttributeError here killed
        # the whole event loop (every connection on the shard)
        host, port = any_server
        s = socket.create_connection((host, port), timeout=5)
        for hostile in (b"5\n", b"null\n", b'"str"\n', b"[1,2]\n"):
            s.sendall(hostile)
            assert b'"error"' in s.recv(4096)
        s2 = socket.create_connection((host, port), timeout=5)
        s2.sendall(b'{"id": 1, "op": "ping"}\n')
        assert b"pong" in s2.recv(4096)  # the loop is still serving
        s.close()
        s2.close()

    def test_escaped_op_and_state_signal_and_wait(self, any_server):
        # regression (native): the op name parsed into a scratch buffer
        # that state-parsing then reused — an escape-containing
        # signal_and_wait was silently parked as a plain barrier (its
        # signal never applied; a cohort would deadlock)
        host, port = any_server
        s = socket.create_connection((host, port), timeout=5)
        f = s.makefile("rw", encoding="utf-8")
        f.write(
            '{"op": "signal\\u005fand\\u005fwait", "id": 9, '
            '"state": "s\\u0074", "target": 1, "timeout": 5}\n'
        )
        f.flush()
        reply = json.loads(f.readline())
        assert reply.get("seq") == 1 and reply.get("ok") is True, reply
        f.write('{"op": "counter", "id": 10, "state": "st"}\n')
        f.flush()
        assert json.loads(f.readline())["count"] == 1
        s.close()


class TestWatchCLI:
    """``tg sync-stats --watch N``: the operator's live-ramp view —
    periodic refreshes of the same one-shot fetch the exporter uses."""

    def test_watch_emits_periodic_frames(self, capsys):
        from testground_tpu.cli.main import main

        srv = SyncServiceServer().start()
        try:
            c = SyncClient(*srv.address, retry=_fast_retry())
            c.signal_entry("w")
            addr = f"{srv.address[0]}:{srv.address[1]}"
            rc = main(
                ["sync-stats", addr, "--watch", "0.1", "--watch-count", "3"]
            )
            assert rc == 0
            out = capsys.readouterr().out
            assert out.count("stats v2") == 3  # three rendered frames
            assert out.count("refresh 0.1s") == 3
            c.close()
        finally:
            srv.stop()

    def test_watch_json_emits_one_payload_per_refresh(self, capsys):
        from testground_tpu.cli.main import main

        srv = SyncServiceServer().start()
        try:
            addr = f"{srv.address[0]}:{srv.address[1]}"
            rc = main(
                [
                    "sync-stats", addr, "--json",
                    "--watch", "0.05", "--watch-count", "2",
                ]
            )
            assert rc == 0
            lines = [
                ln
                for ln in capsys.readouterr().out.splitlines()
                if ln.strip()
            ]
            assert len(lines) == 2
            for ln in lines:
                assert json.loads(ln)["v"] == 2
        finally:
            srv.stop()

    def test_watch_unreachable_first_fetch_fails(self, capsys):
        from testground_tpu.cli.main import main

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        rc = main(
            [
                "sync-stats", f"127.0.0.1:{port}",
                "--timeout", "1", "--watch", "0.1",
            ]
        )
        assert rc == 1
        assert "unreachable" in capsys.readouterr().err


class TestShardedLoops:
    """Cross-shard correctness: with connections spread over N event
    loops, releases and fanout must cross loops exactly like the
    single-loop default (the knob: SyncServiceServer(shards=N) /
    tg-syncsvc --shards N)."""

    @pytest.fixture(params=["python", "native"])
    def sharded_server(self, request):
        if request.param == "python":
            srv = SyncServiceServer(shards=2).start()
            yield srv.address
            srv.stop()
        else:
            from testground_tpu.native import NativeSyncService

            srv = NativeSyncService(
                request.getfixturevalue("native_bin"), shards=2
            )
            yield srv.address
            srv.stop()

    def test_cross_shard_barrier_and_fanout(self, sharded_server):
        host, port = sharded_server
        clients = [
            SyncClient(host, port, namespace="sh:", retry=_fast_retry())
            for _ in range(4)
        ]
        try:
            # barrier across all 4 (round-robin sharding puts them on
            # different loops; the release must fan out across shards)
            results = []
            threads = [
                threading.Thread(
                    target=lambda c=c: results.append(
                        c.signal_and_wait("all", 4, timeout=10)
                    ),
                    daemon=True,
                )
                for c in clients
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)
            assert sorted(results) == [1, 2, 3, 4]
            # pubsub fanout across shards: every client sees every entry
            subs = [c.subscribe("bcast", timeout=10) for c in clients]
            clients[0].publish("bcast", "a")
            clients[3].publish("bcast", "b")
            for sub in subs:
                assert next(sub) == "a"
                assert next(sub) == "b"
            # occupancy accounting survives the spread
            stats = clients[0].sync_stats()
            assert stats["conns"] >= 4
            # regression: a touched state forwarded between loops must
            # be terminal — re-broadcasting it ping-pongs forever and
            # the loops busy-spin at full CPU while completely idle
            cpu0, wall0 = time.process_time(), time.monotonic()
            time.sleep(0.6)
            cpu = time.process_time() - cpu0
            wall = time.monotonic() - wall0
            assert cpu < 0.5 * wall, (
                f"sharded loops burned {cpu:.2f}s CPU over {wall:.2f}s "
                "idle — cross-shard touch ping-pong"
            )
        finally:
            for c in clients:
                c.close()
