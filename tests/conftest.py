"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (the reference's kind-cluster
analog — SURVEY.md §4): multi-chip sharding is validated without TPU
hardware. Must be set before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture()
def tg_home(tmp_path, monkeypatch):
    """Isolated $TESTGROUND_HOME for engine/runner tests."""
    home = tmp_path / "tghome"
    home.mkdir()
    monkeypatch.setenv("TESTGROUND_HOME", str(home))
    return home
