"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (the reference's kind-cluster
analog — SURVEY.md §4): multi-chip sharding is validated without TPU
hardware. Must be set before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment may force-register an accelerator backend from
# sitecustomize (overriding JAX_PLATFORMS); pin the config explicitly so
# tests never dispatch eagerly over a device tunnel.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def tg_home(tmp_path, monkeypatch):
    """Isolated $TESTGROUND_HOME for engine/runner tests."""
    home = tmp_path / "tghome"
    home.mkdir()
    monkeypatch.setenv("TESTGROUND_HOME", str(home))
    return home
