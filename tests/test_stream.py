"""Live observability streaming (docs/OBSERVABILITY.md "Run health
plane"): the ``engine/stream.py`` tail generator, the daemon's
``GET /stream`` route, and ``Client.stream`` — replay-then-close on
finished tasks, live rows while a writer appends, partial-line safety,
family filtering, and bearer-token auth."""

import json
import os
import threading
import time

import pytest

from testground_tpu.client import Client, DaemonError
from testground_tpu.config import EnvConfig
from testground_tpu.daemon import Daemon
from testground_tpu.engine.stream import stream_task_rows

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


# ---------------------------------------------------------- tail generator


class TestTailGenerator:
    def run_dir(self, tmp_path, task_id="task1"):
        d = tmp_path / "plan" / task_id
        d.mkdir(parents=True)
        return d

    def test_finished_task_replays_full_history_then_closes(self, tmp_path):
        d = self.run_dir(tmp_path)
        with open(d / "sim_timeseries.jsonl", "w") as f:
            for i in range(5):
                f.write(json.dumps({"tick": i, "delivered": 1}) + "\n")
        with open(d / "sim_slo.jsonl", "w") as f:
            f.write(json.dumps({"rule": "r", "tick": 3}) + "\n")
        rows = list(
            stream_task_rows(
                str(tmp_path), "plan", "task1", is_done=lambda: True,
                follow=True,
            )
        )
        # full history, tagged, then the generator CLOSED (list() returned)
        tele = [r for r in rows if r["stream"] == "telemetry"]
        slo = [r for r in rows if r["stream"] == "slo"]
        assert [r["tick"] for r in tele] == list(range(5))
        assert len(slo) == 1 and slo[0]["rule"] == "r"
        assert all(r["run"] == "task1" for r in rows)

    def test_no_follow_is_one_sweep(self, tmp_path):
        d = self.run_dir(tmp_path)
        with open(d / "sim_perf.jsonl", "w") as f:
            f.write(json.dumps({"chunk": 0}) + "\n")
        rows = list(
            stream_task_rows(
                str(tmp_path), "plan", "task1",
                is_done=lambda: False,  # still running...
                follow=False,  # ...but a non-follow sweep closes anyway
            )
        )
        assert [r["stream"] for r in rows] == ["perf"]

    def test_concurrent_reader_sees_rows_as_writer_appends(self, tmp_path):
        """The live contract: a reader following a running task receives
        rows the writer appended AFTER the stream started, then the
        stream closes once the task finishes."""
        d = self.run_dir(tmp_path)
        path = d / "sim_timeseries.jsonl"
        path.write_text(json.dumps({"tick": 0}) + "\n")
        done = threading.Event()
        got: list = []

        def reader():
            for row in stream_task_rows(
                str(tmp_path), "plan", "task1",
                is_done=done.is_set, follow=True, poll_secs=0.01,
            ):
                got.append(row)

        th = threading.Thread(target=reader, daemon=True)
        th.start()
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got, "reader saw nothing from the pre-existing file"
        # writer appends while the reader is live
        with open(path, "a") as f:
            f.write(json.dumps({"tick": 1}) + "\n")
        while len(got) < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert [r["tick"] for r in got] == [0, 1]
        done.set()
        th.join(timeout=5)
        assert not th.is_alive(), "stream did not close after the task"

    def test_partial_trailing_line_is_never_consumed(self, tmp_path):
        """A writer mid-``write`` must not produce a torn row: bytes
        after the last newline stay unread until their newline lands."""
        d = self.run_dir(tmp_path)
        path = d / "sim_timeseries.jsonl"
        path.write_text(json.dumps({"tick": 0}) + "\n" + '{"tick": 1, "de')
        done = threading.Event()
        got: list = []

        def reader():
            for row in stream_task_rows(
                str(tmp_path), "plan", "task1",
                is_done=done.is_set, follow=True, poll_secs=0.01,
            ):
                got.append(row)

        th = threading.Thread(target=reader, daemon=True)
        th.start()
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert [r["tick"] for r in got] == [0]
        with open(path, "a") as f:  # complete the torn line
            f.write('livered": 2}\n')
        while len(got) < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert got[1] == {
            "stream": "telemetry", "run": "task1", "tick": 1,
            "delivered": 2,
        }
        done.set()
        th.join(timeout=5)

    def test_multi_run_dirs_are_tagged(self, tmp_path):
        for rid in ("task1-a", "task1-b"):
            d = self.run_dir(tmp_path, rid)
            (d / "sim_perf.jsonl").write_text(
                json.dumps({"chunk": 0, "run": rid}) + "\n"
            )
        # an unrelated task's dir must NOT leak in
        other = self.run_dir(tmp_path, "task2")
        (other / "sim_perf.jsonl").write_text(json.dumps({"chunk": 9}) + "\n")
        rows = list(
            stream_task_rows(
                str(tmp_path), "plan", "task1", is_done=lambda: True,
            )
        )
        assert sorted(r["run"] for r in rows) == ["task1-a", "task1-b"]

    def test_family_filter(self, tmp_path):
        d = self.run_dir(tmp_path)
        (d / "sim_perf.jsonl").write_text(json.dumps({"chunk": 0}) + "\n")
        (d / "sim_timeseries.jsonl").write_text(
            json.dumps({"tick": 0}) + "\n"
        )
        rows = list(
            stream_task_rows(
                str(tmp_path), "plan", "task1", is_done=lambda: True,
                families=("perf",),
            )
        )
        assert [r["stream"] for r in rows] == ["perf"]

    def test_netmatrix_family_streams_and_filters(self, tmp_path):
        """``sim_netmatrix.jsonl`` rows ride the stream tagged
        ``netmatrix`` and the family filter narrows to them."""
        d = self.run_dir(tmp_path)
        (d / "sim_netmatrix.jsonl").write_text(
            json.dumps(
                {"tick": 16, "chunk": 0, "cells": [[0, 1, 4, 4, 4, 0, 0, 0]]}
            )
            + "\n"
        )
        (d / "sim_perf.jsonl").write_text(json.dumps({"chunk": 0}) + "\n")
        rows = list(
            stream_task_rows(
                str(tmp_path), "plan", "task1", is_done=lambda: True,
            )
        )
        assert {r["stream"] for r in rows} == {"netmatrix", "perf"}
        only = list(
            stream_task_rows(
                str(tmp_path), "plan", "task1", is_done=lambda: True,
                families=("netmatrix",),
            )
        )
        assert [r["stream"] for r in only] == ["netmatrix"]
        assert only[0]["cells"] == [[0, 1, 4, 4, 4, 0, 0, 0]]

    def test_large_backlog_drains_in_bounded_chunks(
        self, tmp_path, monkeypatch
    ):
        """A finished soak's replay must stream its backlog chunk by
        chunk, not land it in one allocation — and a partial trailing
        line still survives chunked reads."""
        from testground_tpu.engine import stream as stream_mod

        monkeypatch.setattr(stream_mod, "_READ_CHUNK", 64)
        d = self.run_dir(tmp_path)
        with open(d / "sim_timeseries.jsonl", "w") as f:
            for i in range(100):  # ~2 KB >> the 64-byte chunk
                f.write(json.dumps({"tick": i}) + "\n")
            f.write('{"tick": 100')  # partial: no newline yet
        rows = list(
            stream_task_rows(
                str(tmp_path), "plan", "task1", is_done=lambda: True,
            )
        )
        assert [r["tick"] for r in rows] == list(range(100))

    def test_heartbeat_yields_none_while_idle(self, tmp_path):
        """heartbeat_secs > 0: an idle follow yields None keepalives (the
        daemon turns them into blank ndjson lines) so a quiet soak can't
        trip a client's socket read timeout."""
        done = threading.Event()
        got: list = []

        def reader():
            for row in stream_task_rows(
                str(tmp_path), "plan", "task1",
                is_done=done.is_set, follow=True, poll_secs=0.01,
                heartbeat_secs=0.05,
            ):
                got.append(row)

        th = threading.Thread(target=reader, daemon=True)
        th.start()
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got, "no heartbeat within the deadline"
        assert all(r is None for r in got)
        done.set()
        th.join(timeout=5)

    def test_no_heartbeat_by_default(self, tmp_path):
        d = self.run_dir(tmp_path)
        (d / "sim_timeseries.jsonl").write_text(
            json.dumps({"tick": 0}) + "\n"
        )
        rows = list(
            stream_task_rows(
                str(tmp_path), "plan", "task1", is_done=lambda: True,
            )
        )
        assert None not in rows and len(rows) == 1

    def test_queued_task_waits_for_the_run_dir(self, tmp_path):
        """Before the runner creates the outputs dir the stream yields
        nothing but stays open; rows appear once the run starts."""
        done = threading.Event()
        got: list = []

        def reader():
            for row in stream_task_rows(
                str(tmp_path), "plan", "task1",
                is_done=done.is_set, follow=True, poll_secs=0.01,
            ):
                got.append(row)

        th = threading.Thread(target=reader, daemon=True)
        th.start()
        time.sleep(0.05)
        assert not got and th.is_alive()
        d = self.run_dir(tmp_path)  # the run "starts"
        (d / "sim_timeseries.jsonl").write_text(
            json.dumps({"tick": 0}) + "\n"
        )
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got and got[0]["tick"] == 0
        done.set()
        th.join(timeout=5)


# ------------------------------------------------------------- daemon e2e


def _sim_composition(telemetry=True):
    return {
        "metadata": {"name": "stream-smoke"},
        "global": {
            "plan": "network",
            "case": "ping-pong",
            "builder": "sim:plan",
            "runner": "sim:jax",
            "run_config": {"telemetry": telemetry, "chunk": 16},
        },
        "groups": [{"id": "all", "instances": {"count": 2}}],
    }


def _wait(client, task_id, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        t = client.status(task_id)
        if t["states"][-1]["state"] in ("complete", "canceled"):
            return t
        time.sleep(0.2)
    raise TimeoutError(task_id)


class TestDaemonStream:
    @pytest.fixture()
    def daemon(self, tg_home):
        d = Daemon(env=EnvConfig.load(), listen="localhost:0")
        d.start()
        yield d
        d.stop()

    @pytest.fixture()
    def client(self, daemon):
        return Client(daemon.address)

    def test_stream_replays_finished_task_then_closes(self, client):
        client.import_plan(os.path.join(PLANS, "network"))
        tid = client.run(_sim_composition())
        _wait(client, tid)
        rows = list(client.stream(tid))  # follow=True on a DONE task
        fams = {r["stream"] for r in rows}
        assert "telemetry" in fams  # per-tick counter rows
        assert "perf" in fams  # per-chunk ledger rows
        assert "spans" in fams  # chunk clock
        tele = [r for r in rows if r["stream"] == "telemetry"]
        assert [r["tick"] for r in tele] == list(range(len(tele)))
        assert all(r["run"] == tid for r in rows)
        # family filter narrows server-side
        only_perf = list(client.stream(tid, families=("perf",)))
        assert only_perf and {r["stream"] for r in only_perf} == {"perf"}

    def test_concurrent_reader_sees_live_rows(self, client):
        """Follow a RUNNING task: the reader must receive rows while the
        run is still in flight (state processing), not only a replay."""
        client.import_plan(os.path.join(PLANS, "network"))
        tid = client.run(
            {
                **_sim_composition(),
                "global": {
                    **_sim_composition()["global"],
                    # long enough to still be running when we attach:
                    # max_ticks bounds it; ping-pong finishes on its own
                    "run_config": {"telemetry": True, "chunk": 16},
                },
            }
        )
        live_states: list = []
        rows: list = []
        for row in client.stream(tid):
            rows.append(row)
            if len(live_states) < 3:
                live_states.append(
                    client.status(tid)["states"][-1]["state"]
                )
        assert rows, "stream produced nothing"
        # the stream closed only after completion
        assert client.status(tid)["states"][-1]["state"] == "complete"

    def test_unknown_task_404(self, client):
        with pytest.raises(DaemonError, match="unknown task"):
            list(client.stream("nope"))

    def test_unknown_family_refused(self, client):
        """A typo'd families= must 400 loudly, not follow row-less for
        the task's whole lifetime."""
        client.import_plan(os.path.join(PLANS, "placebo"))
        tid = client.run(
            {
                "metadata": {"name": "p"},
                "global": {
                    "plan": "placebo",
                    "case": "ok",
                    "builder": "exec:py",
                    "runner": "local:exec",
                    "total_instances": 1,
                },
                "groups": [{"id": "all", "instances": {"count": 1}}],
            }
        )
        _wait(client, tid)
        with pytest.raises(DaemonError, match="unknown stream families"):
            list(client.stream(tid, families=("telemety",)))
        # all-blank ("families=,") must 400 too, not follow row-less
        with pytest.raises(DaemonError, match="unknown stream families"):
            list(client.stream(tid, families=(" ",)))

    def test_unauthenticated_stream_refused(self, tg_home):
        home = os.environ["TESTGROUND_HOME"]
        with open(os.path.join(home, ".env.toml"), "w") as f:
            f.write('[daemon]\ntokens = ["sekrit"]\n')
        d = Daemon(env=EnvConfig.load(), listen="localhost:0")
        d.start()
        try:
            with pytest.raises(DaemonError, match="unauthorized"):
                list(Client(d.address).stream("whatever"))
            # the right token gets through to task resolution
            with pytest.raises(DaemonError, match="unknown task"):
                list(Client(d.address, token="sekrit").stream("whatever"))
        finally:
            d.stop()

    def test_watch_cli_renders_stream(self, client, daemon, capsys):
        """`tg watch` against --endpoint: chunk lines + final outcome."""
        from testground_tpu.cli.main import main as tg_main

        client.import_plan(os.path.join(PLANS, "network"))
        tid = client.run(_sim_composition())
        _wait(client, tid)
        rc = tg_main(["--endpoint", daemon.address, "watch", tid])
        out = capsys.readouterr().out
        assert rc == 0
        assert "tick" in out and "peer·t/s" in out  # the header
        assert "run finished" in out
        assert f"task {tid}: outcome success" in out

    def test_negative_metrics_task_limit_clamped(self, tg_home):
        """A negative limit would slice tasks[:-n] (export the OLDEST
        tasks) — the parser clamps it back to 'use the default'."""
        home = os.environ["TESTGROUND_HOME"]
        with open(os.path.join(home, ".env.toml"), "w") as f:
            f.write("[daemon]\nmetrics_task_limit = -1\n")
        assert EnvConfig.load().daemon.metrics_task_limit == 0

    def test_metrics_task_limit_configurable_and_loud(self, tg_home):
        home = os.environ["TESTGROUND_HOME"]
        with open(os.path.join(home, ".env.toml"), "w") as f:
            f.write("[daemon]\nmetrics_task_limit = 1\n")
        d = Daemon(env=EnvConfig.load(), listen="localhost:0")
        d.start()
        try:
            c = Client(d.address)
            c.import_plan(os.path.join(PLANS, "placebo"))
            comp = {
                "metadata": {"name": "p"},
                "global": {
                    "plan": "placebo",
                    "case": "ok",
                    "builder": "exec:py",
                    "runner": "local:exec",
                    "total_instances": 1,
                },
                "groups": [{"id": "all", "instances": {"count": 1}}],
            }
            for _ in range(2):
                _wait(c, c.run(comp))
            text = c.metrics()
            assert "tg_scrape_tasks_total 2" in text
            assert "tg_scrape_tasks_elided 1" in text
            # exactly one task got per-task series under the limit
            assert text.count("tg_task_queued_seconds{") == 1
        finally:
            d.stop()
