"""Sync-plane stats tests (docs/OBSERVABILITY.md "Sync plane").

The observability tier PR 12 gave the coordination plane: histogram bin
math, barrier lifecycle timing units, the wire-versioned ``sync_stats``
v2 schema, python↔native counter-level wire parity (field-for-field on
identical traffic), the ``tg_sync_*`` Prometheus rendering, the
``tg sync-stats`` CLI verb, the metrics exporter, and the version
negotiation rule (clients tolerate v1 servers)."""

from __future__ import annotations

import json
import re
import socket
import threading
import time

import pytest

from testground_tpu.sync import SyncClient, SyncServiceServer
from testground_tpu.sync.stats import (
    PARITY_FIELDS,
    SYNC_OPS,
    TIME_BINS,
    SyncStats,
    bin_edge_us,
    fetch_sync_stats,
    heartbeat_line,
    hist_quantile_us,
    target_bucket,
    time_bin,
)

# ------------------------------------------------------------- bin math


class TestBinMath:
    def test_time_bin_edges(self):
        # bin i covers [2^i, 2^(i+1)) µs; sub-µs lands in bin 0
        assert time_bin(0) == 0
        assert time_bin(0.4) == 0
        assert time_bin(1) == 0
        assert time_bin(1.9) == 0
        assert time_bin(2) == 1
        assert time_bin(3) == 1
        assert time_bin(4) == 2
        assert time_bin((1 << 10) - 1) == 9
        assert time_bin(1 << 10) == 10

    def test_time_bin_clamps_open_bin(self):
        assert time_bin(1 << (TIME_BINS - 1)) == TIME_BINS - 1
        assert time_bin(1 << 40) == TIME_BINS - 1  # way past: clamped

    def test_bin_edges_double(self):
        assert bin_edge_us(0) == 2.0
        assert bin_edge_us(3) == 16.0
        assert bin_edge_us(TIME_BINS - 2) == float(1 << (TIME_BINS - 1))
        assert bin_edge_us(TIME_BINS - 1) == float("inf")

    def test_quantile_empty_and_single(self):
        assert hist_quantile_us([0] * TIME_BINS, 0.5) == 0.0
        bins = [0] * TIME_BINS
        bins[4] = 1  # one sample in [16, 32)µs
        q = hist_quantile_us(bins, 0.5)
        assert 16.0 <= q <= 32.0

    def test_quantile_orders_and_interpolates(self):
        bins = [0] * TIME_BINS
        bins[2] = 50  # [4, 8)
        bins[8] = 50  # [256, 512)
        p25 = hist_quantile_us(bins, 0.25)
        p75 = hist_quantile_us(bins, 0.75)
        assert 4.0 <= p25 < 8.0
        assert 256.0 <= p75 < 512.0
        assert p25 < p75

    def test_quantile_open_bin_clamps_to_lower_edge(self):
        bins = [0] * TIME_BINS
        bins[-1] = 10
        assert hist_quantile_us(bins, 0.99) == float(1 << (TIME_BINS - 1))

    def test_target_bucket_pow2_ceiling(self):
        assert target_bucket(1) == 1
        assert target_bucket(2) == 2
        assert target_bucket(3) == 4
        assert target_bucket(100) == 128
        assert target_bucket(1024) == 1024
        assert target_bucket(10_000) == 16384

    def test_target_bucket_bounded_label_space(self):
        assert target_bucket(50_000_000) == 1 << 20  # capped


# ------------------------------------------------------- recorder units


class TestSyncStatsRecorder:
    def test_op_done_counts_and_bins(self):
        st = SyncStats()
        st.op_done("signal_entry", 5.0)  # bin 2
        st.op_done("signal_entry", 300.0)  # bin 8
        snap = st.snapshot()
        assert snap["ops"]["signal_entry"] == 2
        rec = snap["op_time_us"]["signal_entry"]
        assert rec["count"] == 2
        assert rec["total_us"] == 305
        assert rec["max_us"] == 300
        assert rec["bins"][2] == 1 and rec["bins"][8] == 1
        assert sum(rec["bins"]) == 2

    def test_count_and_time_split_paths_agree(self):
        # the parked-op path counts at dispatch and times at completion
        st = SyncStats()
        st.count_op("barrier")
        st.time_op("barrier", 1000.0)
        snap = st.snapshot()
        assert snap["ops"]["barrier"] == 1
        assert snap["op_time_us"]["barrier"]["count"] == 1

    def test_unknown_ops_ignored(self):
        st = SyncStats()
        st.count_op("nonsense")
        st.op_done("nonsense", 1.0)
        assert "nonsense" not in st.snapshot()["ops"]

    def test_barrier_episode_wall_keyed_by_target(self):
        # deterministic injected clock: armed at first parked waiter,
        # released wall recorded by the FIRST releaser, pow2-bucketed
        now = [100.0]
        st = SyncStats(clock=lambda: now[0])
        st.barrier_parked("s", 3)
        now[0] += 0.5
        st.barrier_parked("s", 3)  # same episode: no re-arm
        now[0] += 1.0
        st.barrier_released("s", 3)
        st.barrier_released("s", 3)
        st.barrier_released("s", 3)
        snap = st.snapshot()["barriers"]
        assert snap["parked"] == 2
        assert snap["released"] == 3
        ep = snap["episodes"]
        assert ep["armed"] == 1 and ep["released"] == 1
        rec = ep["by_target"]["4"]  # target 3 → pow2 bucket 4
        assert rec["count"] == 1
        assert rec["total_ms"] == pytest.approx(1500.0)
        assert rec["max_ms"] == pytest.approx(1500.0)

    def test_barrier_timeout_and_cancel_counters(self):
        st = SyncStats()
        st.barrier_parked("t", 2)
        st.barrier_timed_out("t", 2)
        st.barrier_parked("c", 2)
        st.barrier_canceled("c", 2)
        snap = st.snapshot()["barriers"]
        assert snap["timed_out"] == 1 and snap["canceled"] == 1
        # neither outcome records a release episode
        assert snap["episodes"]["released"] == 0

    def test_failed_episode_closes_and_rearms(self):
        # a timed-out/canceled episode must not pin its arm record: the
        # NEXT barrier on the same (state, target) re-arms and records
        # release timing normally (regression: leaked _armed entries
        # blocked re-arming and crept toward the _MAX_ARMED cap)
        now = [0.0]
        st = SyncStats(clock=lambda: now[0])
        st.barrier_parked("s", 2)
        st.barrier_timed_out("s", 2)
        now[0] += 5.0
        st.barrier_parked("s", 2)  # fresh episode: re-armed
        now[0] += 0.25
        st.barrier_released("s", 2)
        ep = st.snapshot()["barriers"]["episodes"]
        assert ep["armed"] == 2 and ep["released"] == 1
        # the recorded wall is the SECOND episode's 250ms, not 5.25s
        assert ep["by_target"]["2"]["max_ms"] == pytest.approx(250.0)
        assert len(st._armed) == 0  # nothing leaked

    def test_conn_churn_and_hwm(self):
        st = SyncStats()
        for _ in range(3):
            st.conn_open()
        st.conn_close()
        st.conn_open()
        st.conn_evicted()
        snap = st.snapshot()["conn"]
        assert snap["accepts"] == 4
        assert snap["closes"] == 1
        assert snap["evictions"] == 1
        assert snap["hwm"] == 3

    def test_snapshot_carries_every_parity_block(self):
        snap = SyncStats().snapshot()
        assert snap["v"] == 2
        for block, fields in PARITY_FIELDS.items():
            assert block in snap, block
            for f in fields:
                assert f in snap[block], (block, f)
        assert set(snap["ops"]) == set(SYNC_OPS)


# -------------------------------------------------- raw-wire test driver


def _mk(addr):
    s = socket.create_connection(addr, timeout=10)
    s.settimeout(15)
    return s, s.makefile("r", encoding="utf-8")


def _call(s, rf, req):
    s.sendall((json.dumps(req) + "\n").encode())
    return json.loads(rf.readline())


def _drive_script(addr):
    """The scripted identical-traffic workload the wire-parity contract
    compares: signals (+token replay), counter, publishes (+replay),
    ping, a 2-party signal_and_wait, a satisfied barrier, a subscribe,
    a barrier timeout — then the sync_stats snapshot."""
    a, arf = _mk(addr)
    assert _call(a, arf, {"id": 1, "op": "signal_entry", "state": "x",
                          "token": "t1"})["seq"] == 1
    assert _call(a, arf, {"id": 2, "op": "signal_entry", "state": "x",
                          "token": "t1"})["seq"] == 1  # dedup replay
    assert _call(a, arf, {"id": 3, "op": "counter", "state": "x"})["count"] == 1
    assert _call(a, arf, {"id": 4, "op": "publish", "topic": "T",
                          "payload": {"k": 1}, "token": "p1"})["seq"] == 1
    assert _call(a, arf, {"id": 5, "op": "publish", "topic": "T",
                          "payload": {"k": 1}, "token": "p1"})["seq"] == 1
    assert _call(a, arf, {"id": 6, "op": "ping"})["pong"] is True
    b, brf = _mk(addr)
    got = {}

    def sw():
        got["b"] = _call(b, brf, {"id": 7, "op": "signal_and_wait",
                                  "state": "bar", "target": 2,
                                  "timeout": 15})

    t = threading.Thread(target=sw, daemon=True)
    t.start()
    time.sleep(0.2)
    assert _call(a, arf, {"id": 8, "op": "signal_and_wait", "state": "bar",
                          "target": 2, "timeout": 15})["ok"] is True
    t.join(timeout=15)
    assert got["b"]["ok"] is True
    # satisfied-immediately barrier
    assert _call(a, arf, {"id": 9, "op": "barrier", "state": "bar",
                          "target": 2, "timeout": 15})["ok"] is True
    # subscribe: first frame replays the published entry
    frame = _call(a, arf, {"id": 10, "op": "subscribe", "topic": "T"})
    assert frame["entry"] == {"k": 1} and frame["seq"] == 1
    # barrier timeout
    err = _call(a, arf, {"id": 11, "op": "barrier", "state": "never",
                         "target": 9, "timeout": 0.2})
    assert "error" in err
    stats = _call(a, arf, {"id": 12, "op": "sync_stats"})
    a.close()
    b.close()
    return stats


EXPECTED_OPS = {
    "signal_entry": 2,
    "counter": 1,
    "publish": 2,
    "ping": 1,
    "signal_and_wait": 2,
    "barrier": 2,
    "subscribe": 1,
    "sync_stats": 1,
    "hello": 0,
    "bye": 0,
}


@pytest.fixture(scope="session")
def native_bin(tmp_path_factory):
    from testground_tpu.native import build_syncsvc, native_available

    if not native_available():
        pytest.skip("no C++ toolchain for the native sync service")
    return build_syncsvc(str(tmp_path_factory.mktemp("syncsvc-bin")))


# ------------------------------------------------------------ v2 server


class TestServerV2:
    def test_python_server_counts_the_script(self):
        srv = SyncServiceServer().start()
        try:
            stats = _drive_script(srv.address)
        finally:
            srv.stop()
        assert stats["v"] == 2
        for op, want in EXPECTED_OPS.items():
            assert stats["ops"][op] == want, op
        assert stats["dedup"] == {"signal_hits": 1, "publish_hits": 1}
        bar = stats["barriers"]
        # parked: 2 signal_and_wait + satisfied barrier + timeout barrier
        assert bar["parked"] == 4
        assert bar["released"] == 3
        assert bar["timed_out"] == 1
        ps = stats["pubsub"]
        assert ps["published"] == 1  # the replay deduped
        assert ps["topics"] == 1 and ps["entries"] == 1
        assert ps["depth_hwm"] == 1
        # per-op histograms exist for everything the script exercised
        assert stats["op_time_us"]["signal_entry"]["count"] == 2
        assert stats["op_time_us"]["signal_and_wait"]["count"] == 2

    def test_barrier_episode_timing_on_the_wire(self):
        srv = SyncServiceServer().start()
        try:
            stats = _drive_script(srv.address)
        finally:
            srv.stop()
        by_target = stats["barriers"]["episodes"]["by_target"]
        # the 2-party signal_and_wait episode landed in bucket 2 with a
        # positive armed→release wall (the thread parks ~0.2s)
        rec = by_target["2"]
        assert rec["count"] >= 1
        assert rec["total_ms"] > 100.0
        assert rec["max_ms"] >= rec["total_ms"] / rec["count"] - 1e-6

    def test_stats_off_answers_v1_shape(self):
        # the old-server emulation: no "v", occupancy fields only —
        # what the version negotiation rule keys on
        srv = SyncServiceServer(stats=False).start()
        try:
            host, port = srv.address
            stats = fetch_sync_stats(host, port)
        finally:
            srv.stop()
        assert "v" not in stats
        assert set(stats) == {"conns", "waiters", "subs", "boot"}

    def test_client_tolerates_v1_server(self):
        # Client.sync_stats against a pre-stats server still returns
        # the occupancy dict (docstring contract, client.py)
        srv = SyncServiceServer(stats=False).start()
        try:
            c = SyncClient(*srv.address)
            stats = c.sync_stats()
            assert stats["conns"] >= 1 and "waiters" in stats
            assert "v" not in stats
            c.close()
        finally:
            srv.stop()

    def test_client_sync_stats_v2_passthrough(self):
        srv = SyncServiceServer().start()
        try:
            c = SyncClient(*srv.address)
            stats = c.sync_stats()
            assert stats["v"] == 2
            assert stats["ops"]["ping"] >= 1  # its own handshake
            c.close()
        finally:
            srv.stop()

    def test_eviction_counted(self):
        srv = SyncServiceServer(idle_timeout=0.3, evict_grace=0.0).start()
        try:
            host, port = srv.address
            s = socket.create_connection((host, port), timeout=5)
            deadline = time.monotonic() + 10
            evicted = 0
            while time.monotonic() < deadline and not evicted:
                time.sleep(0.2)
                evicted = (fetch_sync_stats(host, port).get("conn") or {}).get(
                    "evictions", 0
                )
            assert evicted >= 1
            s.close()
        finally:
            srv.stop()


# ----------------------------------------------------------- wire parity


class TestWireParity:
    """The native server mirrors the counter-level v2 schema
    field-for-field: identical scripted traffic must produce identical
    counters (PARITY_FIELDS is THE contract both servers implement)."""

    def test_counter_level_parity(self, native_bin):
        from testground_tpu.native import NativeSyncService

        srv_py = SyncServiceServer().start()
        try:
            py = _drive_script(srv_py.address)
        finally:
            srv_py.stop()
        srv_nat = NativeSyncService(native_bin)
        try:
            nat = _drive_script(srv_nat.address)
        finally:
            srv_nat.stop()
        assert py["v"] == 2 and nat["v"] == 2
        for block, fields in PARITY_FIELDS.items():
            for f in fields:
                assert py[block][f] == nat[block][f], (
                    f"{block}.{f}: python={py[block][f]} "
                    f"native={nat[block][f]}"
                )
        # the v1 occupancy fields stay present and equal too
        for k in ("conns", "waiters", "subs"):
            assert py[k] == nat[k], k

    def test_native_stats_off_answers_v1(self, native_bin):
        import subprocess

        proc = subprocess.Popen(
            [native_bin, "--port", "0", "--stats", "0"],
            stdout=subprocess.PIPE,
            text=True,
        )
        try:
            port = int(proc.stdout.readline().split()[1])
            stats = fetch_sync_stats("127.0.0.1", port)
            assert "v" not in stats
            assert set(stats) == {"conns", "waiters", "subs", "boot"}
        finally:
            proc.terminate()
            proc.wait(timeout=10)


# ------------------------------------------------------------ prometheus


class TestSyncPrometheus:
    def _snapshot(self):
        srv = SyncServiceServer().start()
        try:
            return _drive_script(srv.address)
        finally:
            srv.stop()

    def test_valid_exposition(self):
        from testground_tpu.metrics.prometheus import render_sync_prometheus

        text = render_sync_prometheus(self._snapshot())
        line_re = re.compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? "
            r"-?[0-9.e+-]+(\.[0-9]+)?$|"
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*\{[^{}]*le=\"\+Inf\"[^{}]*\} "
            r"[0-9]+$"
        )
        families = set()
        for line in text.strip().splitlines():
            if line.startswith("# HELP") or line.startswith("# TYPE"):
                continue
            assert line_re.match(line), line
            families.add(line.split("{")[0].split(" ")[0])
        for family in (
            "tg_sync_conns",
            "tg_sync_waiters",
            "tg_sync_subs",
            "tg_sync_ops_total",
            "tg_sync_conn_accepts_total",
            "tg_sync_barrier_parked_total",
            "tg_sync_barrier_released_total",
            "tg_sync_barrier_episodes_total",
            "tg_sync_barrier_release_ms_total",
            "tg_sync_pubsub_published_total",
            "tg_sync_dedup_hits_total",
            "tg_sync_op_duration_seconds_bucket",
            "tg_sync_op_duration_seconds_sum",
            "tg_sync_op_duration_seconds_count",
        ):
            assert family in families, family
        # one TYPE header per family, histogram typed as histogram
        assert text.count("# TYPE tg_sync_ops_total") == 1
        assert "# TYPE tg_sync_op_duration_seconds histogram" in text

    def test_histogram_buckets_cumulative_and_reconcile(self):
        from testground_tpu.metrics.prometheus import render_sync_prometheus

        snap = self._snapshot()
        text = render_sync_prometheus(snap)
        buckets = [
            int(m.group(2))
            for m in re.finditer(
                r'tg_sync_op_duration_seconds_bucket\{op="signal_entry"'
                r',le="([^"]+)"\} (\d+)',
                text,
            )
        ]
        assert len(buckets) == TIME_BINS
        assert buckets == sorted(buckets)  # cumulative
        count = int(
            re.search(
                r'tg_sync_op_duration_seconds_count\{op="signal_entry"\} '
                r"(\d+)",
                text,
            ).group(1)
        )
        assert buckets[-1] == count
        assert count == snap["op_time_us"]["signal_entry"]["count"]
        # ops counter reconciles with the snapshot
        m = re.search(r'tg_sync_ops_total\{op="signal_entry"\} (\d+)', text)
        assert int(m.group(1)) == snap["ops"]["signal_entry"]

    def test_barrier_target_labels_bounded_pow2(self):
        from testground_tpu.metrics.prometheus import render_sync_prometheus

        text = render_sync_prometheus(self._snapshot())
        targets = set(
            re.findall(
                r'tg_sync_barrier_episodes_total\{target="(\d+)"\}', text
            )
        )
        assert targets  # the script released episodes
        for t in targets:
            n = int(t)
            assert n & (n - 1) == 0  # pow2 bucket

    def test_v1_snapshot_renders_occupancy_only(self):
        from testground_tpu.metrics.prometheus import render_sync_prometheus

        text = render_sync_prometheus(
            {"conns": 3, "waiters": 1, "subs": 0, "boot": "abc"}
        )
        assert "tg_sync_conns 3" in text
        assert "tg_sync_ops_total" not in text
        assert "tg_sync_op_duration_seconds" not in text


# ------------------------------------------------- surfaces (CLI + HTTP)


class TestSurfaces:
    def test_heartbeat_line_rates_over_interval(self):
        prev = {"ops": {"ping": 10, "signal_entry": 0}}
        cur = {
            "conns": 5,
            "waiters": 2,
            "subs": 1,
            "ops": {"ping": 20, "signal_entry": 90},
            "barriers": {"parked": 4, "released": 3},
            "conn": {"evictions": 1},
        }
        line = heartbeat_line(prev, cur, 10.0)
        assert "conns=5" in line and "waiters=2" in line and "subs=1" in line
        assert "ops/s=10.0" in line  # (110-10)/10
        assert "barriers=3/4" in line and "evictions=1" in line

    def test_heartbeat_line_first_sample(self):
        line = heartbeat_line(None, {"conns": 1, "ops": {"ping": 5}}, 5.0)
        assert "ops/s=1.0" in line

    def test_cli_sync_stats_table_and_json(self, capsys):
        from testground_tpu.cli.main import main

        srv = SyncServiceServer().start()
        try:
            _drive_script(srv.address)
            addr = f"{srv.address[0]}:{srv.address[1]}"
            assert main(["sync-stats", addr]) == 0
            out = capsys.readouterr().out
            assert "stats v2" in out
            assert "signal_entry" in out and "barriers" in out
            assert "barrier release vs fan-in width" in out
            assert main(["sync-stats", addr, "--json"]) == 0
            data = json.loads(capsys.readouterr().out)
            assert data["v"] == 2 and "ops" in data
        finally:
            srv.stop()

    def test_cli_sync_stats_bad_address_and_unreachable(self, capsys):
        from testground_tpu.cli.main import main

        assert main(["sync-stats", "nonsense"]) == 2
        # a port nothing listens on: readable failure, exit 1
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        assert (
            main(["sync-stats", f"127.0.0.1:{port}", "--timeout", "2"]) == 1
        )
        err = capsys.readouterr().err
        assert "unreachable" in err

    def test_render_sync_stats_v1_hint(self):
        from testground_tpu.runners.pretty import render_sync_stats

        out = render_sync_stats(
            {"conns": 2, "waiters": 0, "subs": 0, "boot": "old"}
        )
        assert "v1 server" in out and "occupancy only" in out

    def test_metrics_exporter_scrape(self):
        import urllib.error
        import urllib.request

        from testground_tpu.sync.stats import SyncMetricsExporter

        srv = SyncServiceServer().start()
        exporter = SyncMetricsExporter(srv.address).start()
        try:
            _drive_script(srv.address)
            url = f"http://127.0.0.1:{exporter.port}/metrics"
            resp = urllib.request.urlopen(url, timeout=10)
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
            assert re.search(r"^tg_sync_conns \d+$", text, re.M)
            assert 'tg_sync_ops_total{op="signal_entry"} 2' in text
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{exporter.port}/nope", timeout=10
                )
        finally:
            exporter.stop()
            srv.stop()

    def test_metrics_exporter_unreachable_service_503(self):
        import urllib.error
        import urllib.request

        from testground_tpu.sync.stats import SyncMetricsExporter

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        exporter = SyncMetricsExporter(("127.0.0.1", dead_port)).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{exporter.port}/metrics", timeout=10
                )
            assert ei.value.code == 503
        finally:
            exporter.stop()
