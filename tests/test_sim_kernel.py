"""Unit tests for the sim kernels: link model, calendar transport, sync
tensors (SURVEY.md §4 tier 2 — the mock-reactor tier, except the "mock" is
the real simulator on CPU)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from testground_tpu.sim import net
from testground_tpu.sim.api import FILTER_ACCEPT, FILTER_DROP, FILTER_REJECT
from testground_tpu.sim.net import Calendar, LinkState, deliver, enqueue
from testground_tpu.sim.sync_kernel import (
    make_sub_window,
    make_sync_state,
    update_sync,
)


# transport tests run against BOTH plane layouts: 2-D rows (the
# mesh-sharded form) and flat (the unsharded production form) — see the
# Calendar docstring. Classes that exercise the calendar request the
# fixture via @pytest.mark.usefixtures; sync/specialize tests don't
# touch it and run once.
_CAL_FLAT = False


@pytest.fixture(params=[False, True], ids=["rows", "flat"])
def _calendar_layout(request):
    global _CAL_FLAT
    _CAL_FLAT = request.param
    yield
    _CAL_FLAT = False


def _cal(horizon=8, n=4, slots=2, width=2):
    return Calendar.empty(horizon, n, slots, width, flat=_CAL_FLAT)


def _link(n=4, groups=1, latency=1.0, **kw):
    shape = [latency, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0]
    keys = ["jitter", "bandwidth", "loss", "corrupt", "reorder", "duplicate"]
    for i, k in enumerate(keys, start=1):
        if k in kw:
            shape[i] = kw[k]
    return net.make_link_state(n, groups, shape)


def _send_one(cal, link, src, dst, word, t=0, tick_ms=1.0, n=4, seed=0):
    """Enqueue a single message from src→dst."""
    dsts = jnp.zeros((n, 1), jnp.int32).at[src, 0].set(dst)
    pay = jnp.zeros((n, 1, cal.width), jnp.int32).at[src, 0, 0].set(word)
    valid = jnp.zeros((n, 1), bool).at[src, 0].set(True)
    return enqueue(
        cal,
        link,
        jnp.transpose(dsts),            # [O, N]
        jnp.transpose(pay, (1, 2, 0)),  # [O, W, N]
        jnp.transpose(valid),           # [O, N]
        jnp.int32(t),
        tick_ms,
        jax.random.key(seed),
    )


@pytest.mark.usefixtures("_calendar_layout")
class TestTransport:
    def test_latency_delivery_timing(self):
        """A message shaped with L ms latency arrives exactly ceil(L/tick)
        ticks later (link.go netem delay semantics, in sim time)."""
        cal = _cal()
        link = _link(latency=3.0)
        cal, fb = _send_one(cal, link, src=0, dst=2, word=42, t=0)
        assert int(fb.rejected.sum()) == 0
        for t in range(1, 3):
            cal, inbox = deliver(cal, jnp.int32(t))
            assert not bool(inbox.valid.any()), f"early delivery at {t}"
        cal, inbox = deliver(cal, jnp.int32(3))
        assert bool(inbox.valid[0, 2])
        assert int(inbox.payload[0, 0, 2]) == 42
        assert int(inbox.src[0, 2]) == 0
        # nothing else got a copy
        assert int(inbox.valid.sum()) == 1

    def test_bucket_cleared_after_delivery(self):
        cal = _cal()
        link = _link(latency=2.0)
        cal, _ = _send_one(cal, link, 0, 1, 7, t=0)
        cal, inbox = deliver(cal, jnp.int32(2))
        assert bool(inbox.valid[0, 1])
        cal, inbox2 = deliver(cal, jnp.int32(2 + 8))  # same bucket, next lap
        assert not bool(inbox2.valid.any())

    def test_full_loss_drops(self):
        cal = _cal()
        link = _link(latency=1.0, loss=100.0)
        cal, _ = _send_one(cal, link, 0, 1, 7, t=0)
        total = 0
        for t in range(1, 8):
            cal, inbox = deliver(cal, jnp.int32(t))
            total += int(inbox.valid.sum())
        assert total == 0

    def test_duplicate_delivers_two_copies(self):
        cal = _cal()
        link = _link(latency=1.0, duplicate=100.0)
        cal, _ = _send_one(cal, link, 0, 1, 7, t=0)
        total = 0
        for t in range(1, 8):
            cal, inbox = deliver(cal, jnp.int32(t))
            total += int(inbox.valid[:, 1].sum())
        assert total == 2

    def test_corrupt_flips_a_bit(self):
        cal = _cal()
        link = _link(latency=1.0, corrupt=100.0)
        cal, _ = _send_one(cal, link, 0, 1, 0b1010, t=0)
        cal, inbox = deliver(cal, jnp.int32(1))
        got = int(inbox.payload[0, 0, 1])
        assert got != 0b1010
        assert bin(got ^ 0b1010).count("1") == 1

    def test_drop_filter_blackholes(self):
        cal = _cal()
        link = LinkState(
            egress=_link().egress,
            filters=jnp.full((1, 4), FILTER_DROP, jnp.int32),
            region_of=jnp.zeros((4,), jnp.int32),
        )
        cal, fb = _send_one(cal, link, 0, 1, 7, t=0)
        assert int(fb.rejected.sum()) == 0  # DROP is silent (BLACKHOLE route)
        cal, inbox = deliver(cal, jnp.int32(1))
        assert not bool(inbox.valid.any())

    def test_reject_filter_feeds_back_to_sender(self):
        cal = _cal()
        link = LinkState(
            egress=_link().egress,
            filters=jnp.full((1, 4), FILTER_REJECT, jnp.int32),
            region_of=jnp.zeros((4,), jnp.int32),
        )
        cal, fb = _send_one(cal, link, 0, 1, 7, t=0)
        assert int(fb.rejected[0]) == 1  # PROHIBIT route: sender sees the refusal
        cal, inbox = deliver(cal, jnp.int32(1))
        assert not bool(inbox.valid.any())

    def test_bandwidth_caps_messages_per_tick(self):
        """B bytes/s admits floor(B·tick/MSG_BYTES) messages per tick."""
        n, o = 2, 4
        cal = Calendar.empty(8, n, 8, 1, flat=_CAL_FLAT)
        # 2 msgs/tick at 1ms ticks: B = 2 * 256 * 1000
        link = _link(n=n, latency=1.0, bandwidth=2 * net.MSG_BYTES * 1000.0)
        dsts = jnp.zeros((o, n), jnp.int32).at[:, 0].set(1)
        pay = jnp.ones((o, 1, n), jnp.int32)
        valid = jnp.zeros((o, n), bool).at[:, 0].set(True)
        cal, _ = enqueue(
            cal,
            link,
            dsts,
            pay,
            valid,
            jnp.int32(0),
            1.0,
            jax.random.key(0),
        )
        cal, inbox = deliver(cal, jnp.int32(1))
        assert int(inbox.valid[:, 1].sum()) == 2

    def test_inbox_overflow_drops_excess(self):
        """More same-tick senders than IN_MSGS slots: the surplus drops
        (a full accept queue in the reference)."""
        n = 8
        cal = Calendar.empty(8, n, 2, 1, flat=_CAL_FLAT)  # 2 inbox slots
        link = _link(n=n, latency=1.0)
        dsts = jnp.zeros((1, n), jnp.int32)  # everyone → instance 0
        pay = jnp.ones((1, 1, n), jnp.int32)
        valid = jnp.ones((1, n), bool).at[0, 0].set(False)
        cal, _ = enqueue(
            cal,
            link,
            dsts,
            pay,
            valid,
            jnp.int32(0),
            1.0,
            jax.random.key(0),
        )
        cal, inbox = deliver(cal, jnp.int32(1))
        assert int(inbox.valid[:, 0].sum()) == 2
        assert int(inbox.valid[:, 1:].sum()) == 0


@pytest.mark.usefixtures("_calendar_layout")
class TestBandwidthQueue:
    """HTB-faithful bandwidth ("bandwidth_queue" shaping): excess messages
    are HELD and arrive late — only a full queue tail-drops
    (``pkg/sidecar/link.go:155-183`` HTB rate + token bucket)."""

    FEATURES = ("latency", "bandwidth_queue")

    @staticmethod
    def _bw(rate_msgs_per_tick):
        # rate = B·tick_s/MSG_BYTES at 1 ms ticks
        return rate_msgs_per_tick * net.MSG_BYTES * 1000.0

    def _qlink(self, n, rate, latency=1.0):
        shape = [latency, 0.0, self._bw(rate), 0.0, 0.0, 0.0, 0.0]
        return net.make_link_state(n, 1, shape, track_backlog=True)

    def _send_burst(self, cal, link, src, dst, k, o, t, n, cap=128):
        """k messages src→dst in one tick over o outbox slots."""
        dsts = jnp.zeros((o, n), jnp.int32).at[:, src].set(dst)
        pay = jnp.ones((o, cal.width, n), jnp.int32)
        valid = jnp.zeros((o, n), bool).at[:k, src].set(True)
        return enqueue(
            cal,
            link,
            dsts,
            pay,
            valid,
            jnp.int32(t),
            1.0,
            jax.random.key(t),
            features=self.FEATURES,
            bw_queue_cap=cap,
        )

    def test_sub_one_msg_per_tick_trickles_late(self):
        """A bandwidth below one message per tick (the old admission-cap
        blackhole) DELIVERS every message, late: at 0.5 msg/tick, one
        send per tick arrives every 2 ticks."""
        n = 4
        cal = Calendar.empty(32, n, 2, 1, flat=_CAL_FLAT)
        link = self._qlink(n, rate=0.5)
        for t in range(4):  # one message per tick, ticks 0..3
            cal, fb = self._send_burst(cal, link, 0, 2, k=1, o=1, t=t, n=n)
            assert int(fb.bw_dropped) == 0
            assert int(fb.clamped) == 0
            link = dataclasses.replace(link, backlog=fb.backlog)
            # backlog is link busy time in ticks: each message adds
            # 1/rate = 2 ticks, one tick of service elapses per tick
            assert float(fb.backlog[0]) == pytest.approx(float(t + 1))
        arrivals = []
        for t in range(1, 12):
            cal, inbox = deliver(cal, jnp.int32(t))
            if bool(inbox.valid[:, 2].any()):
                arrivals.append(t)
        assert arrivals == [1, 3, 5, 7]

    def test_burst_spreads_at_service_rate(self):
        """A 4-message burst at 1 msg/tick arrives one per tick, in FIFO
        (outbox) order — deferred, not dropped."""
        n = 4
        cal = Calendar.empty(32, n, 4, 1, flat=_CAL_FLAT)
        link = self._qlink(n, rate=1.0)
        cal, fb = self._send_burst(cal, link, 0, 1, k=4, o=4, t=0, n=n)
        assert int(fb.bw_dropped) == 0
        for t in range(1, 5):
            cal, inbox = deliver(cal, jnp.int32(t))
            assert int(inbox.valid[:, 1].sum()) == 1, f"tick {t}"

    def test_full_queue_tail_drops(self):
        """Only queue overflow drops (HTB's bounded class queue): a burst
        past BW_QUEUE_MSGS loses exactly the tail."""
        n = 4
        cal = Calendar.empty(32, n, 8, 1, flat=_CAL_FLAT)
        link = self._qlink(n, rate=1.0)
        cal, fb = self._send_burst(
            cal, link, 0, 1, k=5, o=5, t=0, n=n, cap=2
        )
        assert int(fb.bw_dropped) == 3
        got = 0
        for t in range(1, 10):
            cal, inbox = deliver(cal, jnp.int32(t))
            got += int(inbox.valid[:, 1].sum())
        assert got == 2

    def test_rate_increase_preserves_fifo(self):
        """A mid-run bandwidth INCREASE must not let new messages
        overtake older queued ones — HTB's class queue is FIFO. The
        backlog is busy TIME, so messages queued under the old rate keep
        their departures and new traffic lines up behind them."""
        n = 4
        cal = Calendar.empty(32, n, 4, 1, flat=_CAL_FLAT)
        link = self._qlink(n, rate=0.1)  # 1 msg per 10 ticks
        # tick 0: two messages — A departs now (arr 1), B queues 10 ticks
        cal, fb = self._send_burst(
            cal, link, 0, 2, k=2, o=2, t=0, n=n, cap=1024
        )
        # tick 1: rate jumps 100×; C must still depart AFTER B (cap is
        # raised: the message bound values standing busy time at the NEW
        # rate — see the approximation note in net.py)
        link = dataclasses.replace(
            self._qlink(n, rate=10.0), backlog=fb.backlog
        )
        cal, fb = self._send_burst(
            cal, link, 0, 2, k=1, o=1, t=1, n=n, cap=1024
        )
        arrivals = []
        for t in range(1, 30):
            cal, inbox = deliver(cal, jnp.int32(t))
            if bool(inbox.valid[:, 2].any()):
                arrivals.append(t)
        # A at 1, B at 11 (10 ticks of 0.1-rate service), C strictly after B
        assert arrivals[0] == 1
        assert arrivals[1] == 11
        assert len(arrivals) == 3 and arrivals[2] > 11

    def test_unshaped_bandwidth_bypasses_queue(self):
        """bandwidth = 0 means unshaped: no deferral, no backlog."""
        n = 4
        cal = Calendar.empty(32, n, 4, 1, flat=_CAL_FLAT)
        link = self._qlink(n, rate=0.0)
        link = dataclasses.replace(  # bandwidth 0 = unlimited
            link, egress=link.egress.at[net.BANDWIDTH].set(0.0)
        )
        cal, fb = self._send_burst(cal, link, 0, 1, k=4, o=4, t=0, n=n)
        assert int(fb.bw_dropped) == 0
        assert float(fb.backlog.sum()) == 0.0
        cal, inbox = deliver(cal, jnp.int32(1))
        assert int(inbox.valid[:, 1].sum()) == 4


@pytest.mark.usefixtures("_calendar_layout")
class TestHorizonClamp:
    """A delay past the calendar horizon is clamped AND counted — netem
    never silently shortens a configured delay (``link.go:169-179``), so
    the clamp must be visible (VERDICT r3 weak #1)."""

    def test_overflowing_latency_is_counted_and_clamped(self):
        cal = _cal(horizon=8)
        link = _link(latency=20.0)  # 20 ticks > horizon-1 = 7
        cal, fb = _send_one(cal, link, src=0, dst=2, word=9, t=0)
        assert int(fb.clamped) == 1
        for t in range(1, 7):
            cal, inbox = deliver(cal, jnp.int32(t))
            assert not bool(inbox.valid.any())
        cal, inbox = deliver(cal, jnp.int32(7))  # arrives at the clamp
        assert bool(inbox.valid[0, 2])

    def test_in_range_latency_not_counted(self):
        cal = _cal(horizon=8)
        link = _link(latency=3.0)
        _, fb = _send_one(cal, link, src=0, dst=2, word=9, t=0)
        assert int(fb.clamped) == 0

    def test_duplicate_copy_at_horizon_edge_is_counted(self):
        """A duplicate's +1 copy clipping back onto its original's tick
        is also a silently-shortened delay — it must join the count."""
        cal = _cal(horizon=8)
        link = _link(latency=7.0, duplicate=100.0)  # delay = horizon-1
        _, fb = _send_one(cal, link, src=0, dst=2, word=9, t=0)
        assert int(fb.clamped) == 1  # the copy, not the original


@pytest.mark.usefixtures("_calendar_layout")
class TestDirectValidate:
    """Debug-mode collision detection for SLOT_MODE='direct': colliding
    sends are reported with the (receiver, slot) instead of silently
    corrupting inbox slots (VERDICT r3 weak #3)."""

    def _send(self, cal, link, dsts, valid, t, validate=True):
        o, n = valid.shape
        pay = jnp.ones((o, cal.width, n), jnp.int32)
        return enqueue(
            cal,
            link,
            dsts,
            pay,
            valid,
            jnp.int32(t),
            1.0,
            jax.random.key(t),
            slot_mode="direct",
            features=("latency",),
            validate=validate,
        )

    def test_same_tick_collision_detected(self):
        n = 4
        cal = _cal(horizon=8, n=n, slots=2)
        link = _link(n=n, latency=1.0)
        # senders 0 AND 1 both target receiver 3, outbox slot 0
        dsts = jnp.zeros((1, n), jnp.int32).at[0, 0].set(3).at[0, 1].set(3)
        valid = jnp.zeros((1, n), bool).at[0, 0].set(True).at[0, 1].set(True)
        _, fb = self._send(cal, link, dsts, valid, t=0)
        assert int(fb.collisions) == 1
        assert fb.collision_where.tolist() == [3, 0]

    def test_cross_tick_overwrite_detected(self):
        """A write onto a slot still occupied from an earlier tick is the
        same corruption (direct mode never stacks)."""
        n = 4
        cal = _cal(horizon=8, n=n, slots=2)
        link = _link(n=n, latency=4.0)  # undelivered for 4 ticks
        dsts = jnp.zeros((1, n), jnp.int32).at[0, 0].set(2)
        valid = jnp.zeros((1, n), bool).at[0, 0].set(True)
        cal, fb = self._send(cal, link, dsts, valid, t=0)
        assert int(fb.collisions) == 0
        # tick 4: arrival bucket (t+4) mod 8 = 0+4 vs 4+4=0 — different
        # buckets; same bucket needs t=8... send again at t=8: bucket
        # (8+4)%8=4 — the SAME bucket as tick 0's, still undelivered
        _, fb2 = self._send(cal, link, dsts, valid, t=8)
        assert int(fb2.collisions) == 1
        assert fb2.collision_where.tolist() == [2, 0]

    def test_clean_direct_traffic_reports_zero(self):
        n = 4
        cal = _cal(horizon=8, n=n, slots=2)
        link = _link(n=n, latency=1.0)
        dsts = jnp.zeros((1, n), jnp.int32).at[0, 0].set(3).at[0, 1].set(2)
        valid = jnp.zeros((1, n), bool).at[0, 0].set(True).at[0, 1].set(True)
        _, fb = self._send(cal, link, dsts, valid, t=0)
        assert int(fb.collisions) == 0

    def test_validate_off_is_silent(self):
        n = 4
        cal = _cal(horizon=8, n=n, slots=2)
        link = _link(n=n, latency=1.0)
        dsts = jnp.zeros((1, n), jnp.int32).at[0, 0].set(3).at[0, 1].set(3)
        valid = jnp.zeros((1, n), bool).at[0, 0].set(True).at[0, 1].set(True)
        _, fb = self._send(cal, link, dsts, valid, t=0, validate=False)
        assert int(fb.collisions) == 0


class TestSyncKernel:
    def test_signal_entry_counts_and_ranks(self):
        """SignalEntry returns 1-based, dense, deterministic sequence
        numbers (sync service atomic-increment semantics)."""
        n, s = 5, 2
        sync = make_sync_state(n, s, 0, 0, 1)
        signals = jnp.zeros((s, n), jnp.int32).at[0, 1].set(1).at[0, 3].set(1)
        sync = update_sync(
            sync,
            signals,
            jnp.zeros((0, 1, n), jnp.int32),
            jnp.zeros((0, n), bool),
            jnp.zeros((0, n), jnp.int32),
        )
        assert int(sync.counts[0]) == 2 and int(sync.counts[1]) == 0
        assert int(sync.last_seq[0, 1]) == 1
        assert int(sync.last_seq[0, 3]) == 2
        # next tick: one more signaller continues the sequence
        signals2 = jnp.zeros((s, n), jnp.int32).at[0, 0].set(1)
        sync = update_sync(
            sync,
            signals2,
            jnp.zeros((0, 1, n), jnp.int32),
            jnp.zeros((0, n), bool),
            jnp.zeros((0, n), jnp.int32),
        )
        assert int(sync.counts[0]) == 3
        assert int(sync.last_seq[0, 0]) == 3
        assert int(sync.last_seq[0, 1]) == 1  # unchanged

    def test_publish_order_and_subscribe_window(self):
        """Every subscriber sees every entry, in one global order
        (PublishSubscribe semantics, benchmarks.go:150-200)."""
        n, t_, cap, pw, k = 4, 1, 8, 2, 3
        sync = make_sync_state(n, 0, t_, cap, pw)
        pub = jnp.zeros((t_, pw, n), jnp.int32)
        pv = jnp.zeros((t_, n), bool)
        for i in (2, 0, 3):  # instance order defines stream order: 0,2,3
            pub = pub.at[0, 0, i].set(100 + i)
            pv = pv.at[0, i].set(True)
        sync = update_sync(sync, jnp.zeros((0, n), jnp.int32), pub, pv,
                           jnp.zeros((t_, n), jnp.int32))
        assert int(sync.stream_len[0]) == 3
        payload, valid = make_sub_window(sync, k)
        got = [int(payload[1, 0, j, 0]) for j in range(3)]
        assert got == [100, 102, 103]
        assert bool(valid[1, 0, :3].all()) and not bool(valid[1, 0, 3:].any())

    def test_subscribe_cursor_advance(self):
        n, t_, cap, pw = 2, 1, 8, 1
        sync = make_sync_state(n, 0, t_, cap, pw)
        pub = jnp.arange(n * pw, dtype=jnp.int32).reshape(t_, pw, n)
        pv = jnp.ones((t_, n), bool)
        sync = update_sync(sync, jnp.zeros((0, n), jnp.int32), pub, pv,
                           jnp.zeros((t_, n), jnp.int32))
        # instance 0 consumes 1 entry
        consume = jnp.zeros((t_, n), jnp.int32).at[0, 0].set(1)
        sync = update_sync(
            sync,
            jnp.zeros((0, n), jnp.int32),
            jnp.zeros((t_, pw, n), jnp.int32),
            jnp.zeros((t_, n), bool),
            consume,
        )
        payload, valid = make_sub_window(sync, 2)
        assert int(payload[0, 0, 0, 0]) == 1  # window starts past consumed
        assert int(payload[1, 0, 0, 0]) == 0  # other cursor unmoved

    def test_stream_overflow_counts_dropped(self):
        n, t_, cap, pw = 4, 1, 2, 1
        sync = make_sync_state(n, 0, t_, cap, pw)
        pub = jnp.ones((t_, pw, n), jnp.int32)
        pv = jnp.ones((t_, n), bool)
        sync = update_sync(sync, jnp.zeros((0, n), jnp.int32), pub, pv,
                           jnp.zeros((t_, n), jnp.int32))
        assert int(sync.stream_len[0]) == cap
        assert int(sync.dropped[0]) == n - cap


@pytest.mark.usefixtures("_calendar_layout")
class TestCrossTickStacking:
    def test_two_ticks_same_bucket_stack_into_slots(self):
        """Messages enqueued on DIFFERENT ticks that land in the same
        arrival bucket must occupy successive inbox slots, not overwrite
        (a TCP accept queue keeps earlier connections). Sender 0 sends at
        t=0 with 2-tick latency, sender 1 at t=1 with 1-tick latency —
        both arrive at t=2."""
        n = 4
        cal = _cal(horizon=8, n=n, slots=2, width=2)
        link_fast = _link(n=n, latency=1.0)
        link_slow = _link(n=n, latency=2.0)
        cal, _ = _send_one(cal, link_slow, src=0, dst=3, word=111, t=0)
        cal, inbox = deliver(cal, jnp.int32(1))
        assert not bool(inbox.valid.any())
        cal, _ = _send_one(cal, link_fast, src=1, dst=3, word=222, t=1)
        cal, inbox = deliver(cal, jnp.int32(2))
        got = set(
            int(inbox.payload[0, s, 3])
            for s in range(2)
            if bool(inbox.valid[s, 3])
        )
        assert got == {111, 222}

    def test_stacking_off_matches_on_for_uniform_latency(self):
        """CROSS_TICK_STACKING=False (api.py contract): with one uniform
        static latency, every bucket fills from a single send tick, so the
        no-stacking transport must deliver identically to the stacking
        one — same-tick fan-in still ranks into successive slots."""
        n = 4
        for stacking in (True, False):
            cal = _cal(horizon=8, n=n, slots=2, width=2)
            link = _link(n=n, latency=2.0)
            # two senders to the same dst on the SAME tick (fan-in of 2)
            dsts = jnp.zeros((1, n), jnp.int32).at[0, 0].set(3).at[0, 1].set(3)
            pay = (
                jnp.zeros((1, 2, n), jnp.int32)
                .at[0, 0, 0].set(111)
                .at[0, 0, 1].set(222)
            )
            valid = jnp.zeros((1, n), bool).at[0, 0].set(True).at[0, 1].set(True)
            cal, _ = enqueue(
                cal, link, dsts, pay, valid, jnp.int32(0), 1.0,
                jax.random.key(0), stacking=stacking,
            )
            cal, inbox = deliver(cal, jnp.int32(2))
            got = sorted(
                int(inbox.payload[0, s, 3])
                for s in range(2)
                if bool(inbox.valid[s, 3])
            )
            assert got == [111, 222], f"stacking={stacking}: {got}"


class TestSpecialize:
    """Per-run static narrowing (SimTestcase.specialize) — no calendar
    involved, so these run once, outside the dual-layout fixture."""

    def test_storm_specialize_narrows_message_axis(self):
        """Storm's per-run specialization sizes OUT_MSGS/IN_MSGS from
        conn_outgoing instead of the manifest upper bound."""
        import os, sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
        from testground_tpu.sim.api import GroupSpec
        from testground_tpu.sim.executor import load_sim_testcases

        plans = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "plans",
        )
        storm = load_sim_testcases(os.path.join(plans, "benchmarks"))["storm"]
        g = GroupSpec(
            id="all", index=0, offset=0, count=8,
            params={"conn_outgoing": "3"},
        )
        # call through the executor's single instantiation path with the
        # tick_ms kwarg — an override whose signature drops it must fail here
        from testground_tpu.sim.executor import instantiate_testcase

        assert type(instantiate_testcase(storm, (g,), 1.0)).OUT_MSGS == 3
        narrowed = storm.specialize((g,), tick_ms=1.0)
        assert narrowed.OUT_MSGS == 3
        # the inbox tail must NOT narrow with k: in-degree is Poisson(k)
        # fixed at dial time, so shrinking IN_MSGS would turn the tail
        # into persistent per-tick droppers
        assert narrowed.IN_MSGS == storm.IN_MSGS
        assert issubclass(narrowed, storm)
        # default bound: class returned unchanged
        g8 = GroupSpec(
            id="all", index=0, offset=0, count=8,
            params={"conn_outgoing": "8"},
        )
        assert storm.specialize((g8,), tick_ms=1.0) is storm

    def test_pingpong_specialize_narrows_horizon(self):
        """Ping-pong sizes its calendar horizon from the shaped latency
        (the calendar is O(horizon*N*slots), so this bounds instances
        per chip)."""
        import os
        from testground_tpu.sim.api import GroupSpec
        from testground_tpu.sim.executor import load_sim_testcases

        plans = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "plans",
        )
        pp = load_sim_testcases(os.path.join(plans, "network"))["ping-pong"]
        g = GroupSpec(
            id="all", index=0, offset=0, count=4,
            params={"latency_ms": "100", "latency2_ms": "10"},
        )
        narrowed = pp.specialize((g,), tick_ms=1.0)
        assert narrowed.MAX_LINK_TICKS == 128  # 100ms + headroom → pow2
        # a latency near the bound keeps the full horizon
        ghi = GroupSpec(
            id="all", index=0, offset=0, count=4,
            params={"latency_ms": "500"},
        )
        assert pp.specialize((ghi,), tick_ms=1.0) is pp


@pytest.mark.usefixtures("_calendar_layout")
class TestCalendarDice:
    def test_shaping_dice_differ_by_key(self):
        """The transport's stochastic draws (loss here) are a function of
        the per-tick key: the same key reproduces the same drop set and a
        different key draws a different one — run-level determinism with
        real randomness across seeds."""
        n, o = 8, 4

        def send_burst(seed):
            cal = _cal(horizon=8, n=n, slots=4, width=2)
            link = _link(n=n, latency=1.0, loss=50.0)
            dsts = jnp.tile(jnp.arange(n, dtype=jnp.int32)[None, :], (o, 1))
            pay = jnp.ones((o, 2, n), jnp.int32)
            valid = jnp.ones((o, n), bool)
            cal, _ = enqueue(
                cal, link, dsts, pay, valid, jnp.int32(0), 1.0,
                jax.random.key(seed),
            )
            _, inbox = deliver(cal, jnp.int32(1))
            return np.asarray(inbox.valid)

        a, b, c = send_burst(0), send_burst(0), send_burst(1)
        assert (a == b).all()  # same key → same drops
        assert 0 < a.sum() < a.size  # 50% loss actually drops some
        assert (a != c).any()  # different key → different drop set

    def test_occupancy_clears_after_delivery(self):
        """A delivered bucket's fill level resets, so its reuse at
        t + horizon starts from slot 0."""
        n = 4
        cal = _cal(horizon=4, n=n, slots=1, width=2)
        link = _link(n=n, latency=1.0)
        cal, _ = _send_one(cal, link, src=0, dst=2, word=5, t=0)
        cal, inbox = deliver(cal, jnp.int32(1))
        assert bool(inbox.valid[0, 2])
        # one full horizon later, the same bucket accepts a new message
        cal, _ = _send_one(cal, link, src=0, dst=2, word=6, t=4)
        cal, inbox = deliver(cal, jnp.int32(5))
        assert bool(inbox.valid[0, 2])
        assert int(inbox.payload[0, 0, 2]) == 6
