"""Run performance ledger (docs/OBSERVABILITY.md "Performance ledger"):
compile/memory/FLOP accounting, throughput gauges, the ``tg perf``
surface, and the Prometheus ``GET /metrics`` exposition.

Pins the acceptance contract: with the ledger active the compiled tick
program is bit-identical (jaxpr equality — the ledger is host-side
bookkeeping, not a program-shaping option) and no host syncs are added
beyond the per-chunk done poll; ``GET /metrics`` serves valid Prometheus
text exposition for a finished task; ``tg perf`` prints the
compile/execute split, peer·ticks/s, HBM high-water mark, and
cost-analysis estimates.
"""

import json
import math
import os
import time
import urllib.request

import pytest

from testground_tpu.api import RunGroup
from testground_tpu.config import EnvConfig
from testground_tpu.sim import engine as engine_mod
from testground_tpu.sim.engine import SimProgram, build_groups
from testground_tpu.sim.executor import load_sim_testcases
from testground_tpu.sim.perf import (
    PERF_FILE,
    PerfLedger,
    compile_analysis,
    device_memory_stats,
    perf_compare,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


def make_groups(*counts, params=None):
    return build_groups(
        [
            RunGroup(id=f"g{i}", instances=c, parameters=dict(params or {}))
            for i, c in enumerate(counts)
        ]
    )


def plan_case(plan, case):
    return load_sim_testcases(os.path.join(PLANS, plan))[case]()


def pingpong_prog(chunk=16, n=4):
    return SimProgram(
        plan_case("network", "ping-pong"), make_groups(n), chunk=chunk
    )


# ------------------------------------------------------ zero overhead


class TestZeroOverheadContract:
    def test_ledger_is_not_program_shaping(self):
        """The acceptance pin: the ledger attaches at run time, never at
        program construction — two identically-configured programs trace
        the identical chunk jaxpr whether or not a ledger will observe
        them (there is no perf knob on SimProgram to diverge on)."""
        import jax

        a, b = pingpong_prog(), pingpong_prog()
        carry = jax.eval_shape(lambda: a.init_carry(0))
        assert str(jax.make_jaxpr(a._chunk_step)(carry)) == str(
            jax.make_jaxpr(b._chunk_step)(carry)
        )

    def test_ledger_adds_no_host_syncs_and_identical_results(
        self, monkeypatch, tmp_path
    ):
        """One done poll per chunk, ledger or not — the per-chunk gauges
        ride the host clock and the AOT pass never executes. The run's
        results are bit-identical either way."""
        calls = {"n": 0}
        real = engine_mod._poll_done

        def counting(done):
            calls["n"] += 1
            return real(done)

        monkeypatch.setattr(engine_mod, "_poll_done", counting)

        def run(ledger):
            calls["n"] = 0
            res = pingpong_prog().run(max_ticks=256, perf=ledger)
            return calls["n"], res

        ledger = PerfLedger(
            4, 16, path=str(tmp_path / PERF_FILE), aot=True
        )
        syncs_off, res_off = run(None)
        syncs_on, res_on = run(ledger)
        ledger.close()
        assert syncs_on == syncs_off
        assert res_on["ticks"] == res_off["ticks"]
        assert (res_on["status"] == res_off["status"]).all()
        assert res_on["msgs_delivered"] == res_off["msgs_delivered"]
        # ...while the ledger saw every chunk and the AOT split
        assert ledger.rows_written == res_on["ticks"] // 16
        assert ledger.summary()["compile"]["lower_secs"] >= 0


# ---------------------------------------------------------- the ledger


class TestPerfLedger:
    def test_rows_and_summary_conserve(self, tmp_path):
        path = str(tmp_path / PERF_FILE)
        ledger = PerfLedger(10, 8, ident={"run": "r"}, path=path, aot=False)
        assert not ledger.wants_aot
        for i in range(4):
            ledger.on_chunk(i, (i + 1) * 8, 8, 0.25)
        ledger.close()
        rows = [json.loads(line) for line in open(path)]
        assert len(rows) == 4 == ledger.rows_written
        for i, row in enumerate(rows):
            assert row["run"] == "r"
            assert row["chunk"] == i
            assert row["tick"] == (i + 1) * 8
            assert row["ticks_per_sec"] == pytest.approx(32.0)
            assert row["peer_ticks_per_sec"] == pytest.approx(320.0)
        s = ledger.summary()
        ex = s["execute"]
        assert ex["chunks"] == 4 and ex["ticks"] == 32
        assert ex["wall_secs"] == pytest.approx(
            sum(r["wall_secs"] for r in rows)
        )
        assert ex["peer_ticks_per_sec"] == pytest.approx(320.0)
        # steady excludes the (compile-bearing) first chunk
        assert ex["steady_chunks"] == 3
        assert ex["steady_peer_ticks_per_sec"] == pytest.approx(320.0)
        assert s["series"] == {"rows": 4, "file": PERF_FILE}

    def test_no_path_only_counts(self):
        ledger = PerfLedger(2, 4, path=None, aot=False)
        ledger.on_chunk(0, 4, 4, 0.1)
        ledger.close()
        assert ledger.rows_written == 1
        assert "file" not in ledger.summary()["series"]

    def test_warmup_2_excludes_the_mesh_retrace_dispatch(self):
        # on a multi-device mesh the SECOND dispatch retraces at the
        # GSPMD sharding fixed point (engine.run) — with warmup=2 its
        # wall must not pollute steady throughput
        ledger = PerfLedger(10, 8, aot=False, warmup=2)
        ledger.on_chunk(0, 8, 8, 5.0)  # trace + compile
        ledger.on_chunk(1, 16, 8, 3.0)  # sharding fixed-point retrace
        for i in range(2, 6):
            ledger.on_chunk(i, (i + 1) * 8, 8, 0.25)
        ex = ledger.summary()["execute"]
        assert ex["chunks"] == 6 and ex["steady_chunks"] == 4
        assert ex["steady_peer_ticks_per_sec"] == pytest.approx(320.0)

    def test_aot_harvest_on_cpu(self):
        """The AOT pass's harvest: on the CPU backend XLA provides a
        cost analysis (flops, bytes accessed) and a memory analysis
        (argument/temp/output bytes) for the chunk program."""
        import jax

        prog = pingpong_prog(chunk=8, n=2)
        carry = jax.jit(lambda: prog.init_carry(0))()
        compiled = prog.compiled_chunk().lower(carry).compile()
        got = compile_analysis(compiled)
        assert got.get("flops", 0) > 0
        assert got.get("bytes_accessed", 0) > 0
        assert got.get("argument_bytes", 0) > 0
        assert got["peak_bytes"] >= got.get("temp_bytes", 0)

    def test_rows_carry_flop_rates_after_on_compile(self, tmp_path):
        class FakeCompiled:
            def cost_analysis(self):
                return {"flops": 1000.0, "bytes accessed": 4000.0}

            def memory_analysis(self):
                return None

        ledger = PerfLedger(2, 4, path=None, aot=True)
        ledger.on_compile(0.5, 1.5, FakeCompiled())
        ledger.on_chunk(0, 4, 4, 0.5)
        s = ledger.summary()
        assert s["compile"] == {
            "lower_secs": 0.5,
            "compile_secs": 1.5,
            "flops": 1000.0,
            "bytes_accessed": 4000.0,
        }


class TestDeviceMemoryStats:
    """The ONE memory_stats probe (satellite: deduped from the runner
    healthcheck and the executor precheck) — normalizes key presence
    and never raises."""

    def test_normalizes_and_filters_keys(self):
        class Dev:
            def memory_stats(self):
                return {
                    "bytes_in_use": 10,
                    "peak_bytes_in_use": 20,
                    "bytes_limit": 100,
                    "largest_free_block_bytes": 5,  # dropped
                    "pool_bytes": "n/a",  # non-numeric dropped
                }

        assert device_memory_stats(Dev()) == {
            "bytes_in_use": 10,
            "peak_bytes_in_use": 20,
            "bytes_limit": 100,
        }

    def test_missing_keys_and_absent_stats(self):
        class Partial:
            def memory_stats(self):
                return {"bytes_in_use": 7}

        class NoneStats:
            def memory_stats(self):
                return None

        class Raises:
            def memory_stats(self):
                raise RuntimeError("backend says no")

        class NoMethod:
            pass

        assert device_memory_stats(Partial()) == {"bytes_in_use": 7}
        assert device_memory_stats(NoneStats()) == {}
        assert device_memory_stats(Raises()) == {}
        assert device_memory_stats(NoMethod()) == {}

    def test_healthcheck_and_precheck_share_the_probe(self):
        """No clone survives: the runner healthcheck and executor
        precheck modules reference the shared helper, not their own
        memory_stats probing."""
        import inspect

        from testground_tpu.sim import executor, runner

        assert "device_memory_stats" in inspect.getsource(
            executor._precheck_device_memory
        )
        assert "device_memory_stats" in inspect.getsource(
            runner.SimJaxRunner.healthcheck
        )


# ------------------------------------------------------------- compare


class TestPerfCompare:
    PAYLOAD = {
        "sim": {"compile_secs": 2.0, "wall_secs": 10.0},
        "perf": {
            "execute": {
                "steady_peer_ticks_per_sec": 1000.0,
                "wall_secs": 8.0,
            }
        },
    }

    def test_against_bench_line(self):
        bench = {
            "metric": "sim_peer_ticks_per_sec",
            "value": 2000.0,
            "compile_secs": 4.0,
        }
        lines = perf_compare(self.PAYLOAD, bench, label="B")
        assert any("x0.500" in ln for ln in lines)  # both ratios halve
        assert sum("x0.500" in ln for ln in lines) == 2

    def test_against_bench_trajectory_wrapper(self):
        wrapper = {
            "n": 5,
            "tail": 'noise\n# log\n{"metric": "sim_peer_ticks_per_sec", '
            '"value": 500.0}',
        }
        lines = perf_compare(self.PAYLOAD, wrapper)
        assert any("x2.000" in ln for ln in lines)

    def test_against_prior_perf_payload(self):
        lines = perf_compare(self.PAYLOAD, self.PAYLOAD)
        assert any("x1.000" in ln for ln in lines)

    def test_nothing_comparable_degrades_readably(self):
        lines = perf_compare({"sim": {}}, {"whatever": 1})
        assert len(lines) == 1 and "no comparable" in lines[0]

    def test_nan_baseline_fields_are_ignored(self):
        # json.loads admits NaN/Infinity literals — a corrupted baseline
        # must drop those fields, not print 'xnan' ratios
        baseline = json.loads(
            '{"metric": "sim_peer_ticks_per_sec", "value": NaN, '
            '"compile_secs": Infinity}'
        )
        lines = perf_compare(self.PAYLOAD, baseline, label="B")
        assert len(lines) == 1 and "no comparable" in lines[0]


# ---------------------------------------------------------- prometheus


class TestPrometheusRender:
    def _task(self, **kw):
        from testground_tpu.engine.task import (
            DatedState,
            State,
            Task,
            TaskType,
        )

        t = Task(
            id=kw.get("id", "t1"),
            type=TaskType.RUN,
            plan=kw.get("plan", "network"),
            case=kw.get("case", "ping-pong"),
            states=[
                DatedState(state=State.SCHEDULED, created=1.0),
                DatedState(state=State.COMPLETE, created=2.0),
            ],
            result=kw.get("result"),
        )
        return t

    def test_valid_exposition_for_a_finished_task(self):
        import re

        from testground_tpu.metrics.prometheus import render_prometheus

        result = {
            "outcome": "success",
            "perf": {"queued_secs": 0.25, "runner_wall_secs": {"r1": 3.5}},
            "journal": {
                "sim": {
                    "ticks": 224,
                    "wall_secs": 1.5,
                    "compile_secs": 1.2,
                    "devices": 1,
                    "carry_bytes": 4096,
                    "msgs_sent": 10,
                    "msgs_delivered": 8,
                    "msgs_dropped": 1,
                    "msgs_rejected": 1,
                    "msgs_in_flight": 0,
                    "msgs_fault_dropped": 0,
                    "perf": {
                        "compile": {
                            "lower_secs": 0.4,
                            "compile_secs": 0.7,
                            "flops": 4872.0,
                            "bytes_accessed": 69231.0,
                        },
                        "execute": {"steady_peer_ticks_per_sec": 26901.0},
                        "hbm": {"peak_bytes": 1 << 30},
                    },
                }
            },
        }
        text = render_prometheus([self._task(result=result)])
        # every non-comment line must match the exposition grammar
        line_re = re.compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? "
            r"-?[0-9.e+-]+(\.[0-9]+)?$"
        )
        families = set()
        for line in text.strip().splitlines():
            if line.startswith("# HELP") or line.startswith("# TYPE"):
                continue
            assert line_re.match(line), line
            families.add(line.split("{")[0])
        for family in (
            "tg_tasks",
            "tg_task_queued_seconds",
            "tg_task_runner_wall_seconds",
            "tg_run_msgs_total",
            "tg_run_ticks",
            "tg_run_compile_seconds",
            "tg_run_peer_ticks_per_second",
            "tg_run_lower_seconds",
            "tg_run_xla_compile_seconds",
            "tg_run_est_flops_per_chunk",
            "tg_run_hbm_peak_bytes",
        ):
            assert family in families, family
        # the flow label carries the conservation legs
        assert 'flow="delivered"' in text and 'flow="sent"' in text
        # each family declares HELP + TYPE exactly once
        assert text.count("# TYPE tg_run_msgs_total") == 1

    def test_escapes_labels_and_skips_nan(self):
        from testground_tpu.metrics.prometheus import render_prometheus

        result = {
            "journal": {
                "sim": {"ticks": float("nan"), "wall_secs": 1.0}
            }
        }
        t = self._task(id='we"ird\\id', plan="a\nb", result=result)
        text = render_prometheus([t])
        assert 'task="we\\"ird\\\\id"' in text
        assert 'plan="a\\nb"' in text
        assert "nan" not in text.lower().replace("instance", "")
        assert "tg_run_ticks" not in text  # NaN metric dropped entirely
        assert "tg_run_wall_seconds" in text

    def test_empty_task_list(self):
        from testground_tpu.metrics.prometheus import render_prometheus

        # no task-derived series — but the scrape-coverage gauges are
        # always present (truncation is never silent, even at zero)
        text = render_prometheus([])
        assert "tg_scrape_tasks_total 0" in text
        assert "tg_scrape_tasks_elided 0" in text
        lines = [
            ln
            for ln in text.splitlines()
            if ln and not ln.startswith("#")
        ]
        assert lines == [
            "tg_scrape_tasks_total 0",
            "tg_scrape_tasks_elided 0",
        ]

    def test_per_task_limit_bounds_series_not_counts(self):
        from testground_tpu.metrics.prometheus import render_prometheus

        result = {"perf": {"queued_secs": 0.5}}
        tasks = [
            self._task(id=f"t{i}", result=result) for i in range(5)
        ]
        text = render_prometheus(tasks, per_task_limit=2)
        # the aggregate counts the FULL store (honest on busy daemons)...
        assert 'tg_tasks{state="complete",type="run"} 5' in text
        # ...while task-labeled series stop at the cardinality window
        assert text.count("tg_task_queued_seconds{") == 2
        assert 'task="t0"' in text and 'task="t4"' not in text


# ------------------------------------------------- payload + artifacts


class TestPerfPayload:
    def test_task_perf_payload_shape(self):
        from testground_tpu.engine.task import (
            DatedState,
            State,
            Task,
            TaskType,
        )

        t = Task(
            id="t1",
            type=TaskType.RUN,
            plan="p",
            case="c",
            states=[DatedState(state=State.COMPLETE, created=1.0)],
            result={
                "outcome": "success",
                "perf": {"queued_secs": 0.1},
                "journal": {
                    "sim": {"ticks": 3, "perf": {"instances": 2}}
                },
            },
        )
        p = t.perf_payload()
        assert p["task_id"] == "t1"
        assert p["perf"] == {"instances": 2}
        assert p["sim"] == {"ticks": 3}  # nested ledger lifted out
        assert p["task"] == {"queued_secs": 0.1}

    def test_perf_payload_tolerates_missing_everything(self):
        from testground_tpu.engine.task import (
            DatedState,
            State,
            Task,
            TaskType,
        )

        t = Task(
            id="t2",
            type=TaskType.BUILD,
            states=[DatedState(state=State.COMPLETE, created=1.0)],
        )
        p = t.perf_payload()
        assert p["perf"] == {} and p["sim"] == {} and p["task"] == {}


class TestArtifactWhitelist:
    def test_flat_and_nested_names(self):
        from testground_tpu.daemon.server import _Handler

        rel = _Handler._artifact_relpath
        assert rel("sim_perf.jsonl") == "sim_perf.jsonl"
        assert rel("sim_trace.jsonl") == "sim_trace.jsonl"
        # nested SDK profile dumps: <group>/<instance>/profile-cpu.pstats
        assert rel("single/0/profile-cpu.pstats") == os.path.join(
            "single", "0", "profile-cpu.pstats"
        )
        # traversal and junk are refused
        for bad in (
            "../../etc/passwd",
            "single/../../../profile-cpu.pstats",
            "single/0/../profile-cpu.pstats",
            "/etc/profile-cpu.pstats",
            "single/0/other.pstats",
            "a/b/c/d/e/profile-cpu.pstats",
            "profile-cpu.pstats.evil",
            "",
        ):
            assert rel(bad) is None, bad


# -------------------------------------------------------------- viewer


class TestViewerPerfFamily:
    def test_expand_perf_row(self):
        from testground_tpu.metrics.viewer import expand_perf_row

        row = {
            "run": "r",
            "plan": "p",
            "case": "c",
            "tick": 16,
            "chunk": 0,
            "wall_secs": 0.1,
            "peer_ticks_per_sec": 320.0,
        }
        out = {r["name"]: r for r in expand_perf_row(row)}
        assert set(out) == {
            "sim.perf.wall_secs",
            "sim.perf.peer_ticks_per_sec",
        }
        assert out["sim.perf.peer_ticks_per_sec"]["mean"] == 320.0
        assert out["sim.perf.wall_secs"]["group_id"] == "_run"
        assert out["sim.perf.wall_secs"]["tick"] == 16

    def test_viewer_surfaces_perf_measurements(self, tg_home):
        from testground_tpu.metrics import Viewer, measurement_name

        env = EnvConfig.load()
        run_dir = os.path.join(env.dirs.outputs(), "p", "r1")
        os.makedirs(run_dir)
        with open(os.path.join(run_dir, PERF_FILE), "w") as f:
            for i in range(3):
                f.write(
                    json.dumps(
                        {
                            "run": "r1",
                            "plan": "p",
                            "case": "c",
                            "tick": (i + 1) * 8,
                            "chunk": i,
                            "wall_secs": 0.5,
                            "ticks_per_sec": 16.0,
                        }
                    )
                    + "\n"
                )
        v = Viewer(env)
        names = v.get_measurements("p", "c")
        assert measurement_name("p", "c", "sim.perf.ticks_per_sec") in names
        rows = v.get_data("p", "c", "sim.perf.wall_secs", run_id="r1")
        assert len(rows) == 3
        assert rows[0].fields["mean"] == 0.5
        # the chunk index is identity, not a measurement
        assert measurement_name("p", "c", "sim.perf.chunk") not in names


# --------------------------------------------------------- end-to-end


@pytest.fixture(scope="class")
def perf_daemon(tmp_path_factory):
    # class-scoped (one sim run feeds every surface test below), so no
    # function-scoped monkeypatch — save/restore the env var by hand
    prev = os.environ.get("TESTGROUND_HOME")
    os.environ["TESTGROUND_HOME"] = str(
        tmp_path_factory.mktemp("tghome-perf")
    )
    from testground_tpu.daemon import Daemon

    d = Daemon(env=EnvConfig.load(), listen="localhost:0")
    d.start()
    yield d
    d.stop()
    if prev is None:
        os.environ.pop("TESTGROUND_HOME", None)
    else:
        os.environ["TESTGROUND_HOME"] = prev


@pytest.fixture(scope="class")
def perf_task(perf_daemon):
    from testground_tpu.client import Client

    client = Client(perf_daemon.address)
    client.import_plan(os.path.join(PLANS, "network"))
    task_id = client.run(
        {
            "global": {
                "plan": "network",
                "case": "ping-pong",
                "builder": "sim:plan",
                "runner": "sim:jax",
                "total_instances": 2,
                "run_config": {"chunk": 16},
            },
            "groups": [{"id": "all", "instances": {"count": 2}}],
        }
    )
    deadline = time.time() + 180
    while time.time() < deadline:
        t = client.status(task_id)
        if t["states"][-1]["state"] in ("complete", "canceled"):
            assert t["outcome"] == "success"
            return task_id
        time.sleep(0.2)
    raise TimeoutError(task_id)


class TestPerfSurfaceE2E:
    def test_perf_route_and_client(self, perf_daemon, perf_task):
        from testground_tpu.client import Client

        data = Client(perf_daemon.address).perf(perf_task)
        assert data["task_id"] == perf_task
        assert data["outcome"] == "success"
        perf = data["perf"]
        assert perf["execute"]["peer_ticks_per_sec"] > 0
        assert perf["compile"]["lower_secs"] >= 0
        assert perf["series"]["file"] == PERF_FILE
        assert data["task"]["queued_secs"] >= 0
        assert data["task"]["runner_wall_secs"]

    def test_perf_route_404s_unknown_task(self, perf_daemon):
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                perf_daemon.address + "/perf?task_id=ghost", timeout=30
            )
        assert ei.value.code == 404

    def test_metrics_route_serves_prometheus(self, perf_daemon, perf_task):
        resp = urllib.request.urlopen(
            perf_daemon.address + "/metrics", timeout=30
        )
        assert resp.headers["Content-Type"].startswith("text/plain")
        assert "version=0.0.4" in resp.headers["Content-Type"]
        text = resp.read().decode()
        assert f'task="{perf_task}"' in text
        assert "tg_tasks{" in text
        assert 'tg_run_msgs_total{' in text and 'flow="delivered"' in text
        assert "tg_run_peer_ticks_per_second{" in text
        assert "# TYPE tg_run_msgs_total counter" in text

    def test_metrics_via_client(self, perf_daemon, perf_task):
        from testground_tpu.client import Client

        text = Client(perf_daemon.address).metrics()
        assert "tg_tasks" in text

    def test_cli_perf_renders_summary(self, perf_daemon, perf_task, capsys):
        """``tg perf <task>`` against the daemon prints the
        compile/execute split, peer·ticks/s, the HBM line, and the
        cost-analysis estimates (the acceptance criterion's CLI half)."""
        from testground_tpu.cli.main import main

        rc = main(["--endpoint", perf_daemon.address, "perf", perf_task])
        assert rc == 0
        out = capsys.readouterr().out
        assert "compile" in out and "AOT lower" in out
        assert "peer·ticks/s" in out
        assert "hbm" in out  # present even when the backend has no stats
        assert "flops" in out  # CPU cost analysis
        assert "network:ping-pong" in out

    def test_cli_perf_compare(
        self, perf_daemon, perf_task, tmp_path, capsys
    ):
        from testground_tpu.cli.main import main

        baseline = tmp_path / "BENCH_r99.json"
        baseline.write_text(
            json.dumps(
                {
                    "metric": "sim_peer_ticks_per_sec",
                    "value": 1000.0,
                    "compile_secs": 10.0,
                }
            )
        )
        rc = main(
            [
                "--endpoint",
                perf_daemon.address,
                "perf",
                perf_task,
                "--compare",
                str(baseline),
            ]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "vs BENCH_r99.json" in out
        assert "peer·ticks/s" in out and " vs " in out

    def test_cli_perf_json_round_trips(
        self, perf_daemon, perf_task, capsys
    ):
        from testground_tpu.cli.main import main

        rc = main(
            ["--endpoint", perf_daemon.address, "perf", perf_task, "--json"]
        )
        assert rc == 0
        data = json.loads(capsys.readouterr().out)
        assert data["perf"]["execute"]["chunks"] > 0

    def test_perf_artifact_served(self, perf_daemon, perf_task):
        resp = urllib.request.urlopen(
            perf_daemon.address
            + f"/artifact?task_id={perf_task}&name=sim_perf.jsonl",
            timeout=30,
        )
        rows = [
            json.loads(line)
            for line in resp.read().decode().splitlines()
            if line.strip()
        ]
        assert rows and all("peer_ticks_per_sec" in r for r in rows)
        wall = sum(r["wall_secs"] for r in rows)
        assert wall > 0 and math.isfinite(wall)


class TestPerfGating:
    def test_disable_metrics_suppresses_ledger(self, tg_home):
        import threading

        from testground_tpu.api import RunInput
        from testground_tpu.engine import Outcome
        from testground_tpu.rpc import discard_writer
        from testground_tpu.sim.executor import (
            SimJaxConfig,
            execute_sim_run,
        )

        env = EnvConfig.load()
        job = RunInput(
            run_id="noperf",
            test_plan="placebo",
            test_case="ok",
            total_instances=2,
            groups=[
                RunGroup(
                    id="all",
                    instances=2,
                    artifact_path=os.path.join(PLANS, "placebo"),
                    parameters={},
                )
            ],
            env=env,
            disable_metrics=True,
        )
        job.runner_config = SimJaxConfig(chunk=8)
        out = execute_sim_run(job, discard_writer(), threading.Event())
        assert out.result.outcome == Outcome.SUCCESS
        run_dir = os.path.join(env.dirs.outputs(), "placebo", "noperf")
        assert not os.path.exists(os.path.join(run_dir, PERF_FILE))
        assert "perf" not in out.result.journal["sim"]

    def test_perf_false_suppresses_ledger(self, tg_home):
        import threading

        from testground_tpu.api import RunInput
        from testground_tpu.engine import Outcome
        from testground_tpu.rpc import discard_writer
        from testground_tpu.sim.executor import (
            SimJaxConfig,
            execute_sim_run,
        )

        env = EnvConfig.load()
        job = RunInput(
            run_id="perfoff",
            test_plan="placebo",
            test_case="ok",
            total_instances=2,
            groups=[
                RunGroup(
                    id="all",
                    instances=2,
                    artifact_path=os.path.join(PLANS, "placebo"),
                    parameters={},
                )
            ],
            env=env,
        )
        job.runner_config = SimJaxConfig(chunk=8, perf=False)
        out = execute_sim_run(job, discard_writer(), threading.Event())
        assert out.result.outcome == Outcome.SUCCESS
        run_dir = os.path.join(env.dirs.outputs(), "placebo", "perfoff")
        assert not os.path.exists(os.path.join(run_dir, PERF_FILE))
        assert "perf" not in out.result.journal["sim"]
