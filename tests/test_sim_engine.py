"""SimProgram tests: whole-plan state machines stepped by the jitted tick
loop, on the 8-device CPU mesh and unsharded (SURVEY.md §4 — the sim:jax
runner on CPU is the "kind cluster" equivalent)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from testground_tpu.api import RunGroup
from testground_tpu.sim.api import (
    CRASH,
    FAILURE,
    RUNNING,
    SUCCESS,
    SimTestcase,
)
from testground_tpu.sim.engine import SimProgram, build_groups
from testground_tpu.sim.executor import load_sim_testcases

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


def make_groups(*counts, params=None):
    return build_groups(
        [
            RunGroup(id=f"g{i}", instances=c, parameters=dict(params or {}))
            for i, c in enumerate(counts)
        ]
    )


def plan_case(plan, case):
    return load_sim_testcases(os.path.join(PLANS, plan))[case]()


def mesh8():
    devs = jax.devices()
    assert len(devs) == 8, "conftest must provide 8 virtual CPU devices"
    return jax.sharding.Mesh(np.asarray(devs), ("i",))


class TestPlacebo:
    def test_ok_all_success(self):
        prog = SimProgram(plan_case("placebo", "ok"), make_groups(4))
        res = prog.run(max_ticks=64)
        assert (res["status"] == SUCCESS).all()
        assert (res["finished_at"] == 0).all()

    def test_abort_and_panic(self):
        for case, code in (("abort", FAILURE), ("panic", CRASH)):
            prog = SimProgram(plan_case("placebo", case), make_groups(3))
            res = prog.run(max_ticks=64)
            assert (res["status"] == code).all()

    def test_stall_hits_max_ticks(self):
        prog = SimProgram(plan_case("placebo", "stall"), make_groups(2), chunk=8)
        res = prog.run(max_ticks=32)
        assert (res["status"] == RUNNING).all()
        assert res["ticks"] >= 32

    def test_metrics_counts_to_ten(self):
        tc = plan_case("placebo", "metrics")
        prog = SimProgram(tc, make_groups(5))
        res = prog.run(max_ticks=64)
        assert (res["status"] == SUCCESS).all()
        assert (res["states"][0]["counter"] == 10).all()
        m = tc.collect_metrics(res["groups"][0], res["states"][0], res["status"])
        assert (np.asarray(m["placebo.counter"]) == 10).all()

    def test_seed_determinism(self):
        """The simulator is deterministic: the same seed reproduces a run
        bit-for-bit (the property that makes in-sim race debugging
        tractable where the reference relies on behavioral assertions —
        SURVEY.md §5 'race detection'), and a different seed actually
        changes the stochastic draws."""
        params = {
            "latency_ms": "3",
            "latency2_ms": "2",
            "tolerance_ms": "15",
        }

        def run(seed):
            prog = SimProgram(
                plan_case("network", "ping-pong"),
                make_groups(16, params=params),
                chunk=16,
            )
            return prog.run(seed=seed, max_ticks=256)

        a, b = run(7), run(7)
        assert (a["status"] == b["status"]).all()
        for key in ("rtt1", "rtt2"):
            assert (a["states"][0][key] == b["states"][0][key]).all()

    def test_sharded_matches_unsharded(self):
        """vmap-vs-ground-truth (BASELINE config 2 spirit): the mesh must
        not change results."""
        res1 = SimProgram(plan_case("placebo", "metrics"), make_groups(16)).run(
            max_ticks=64
        )
        res8 = SimProgram(
            plan_case("placebo", "metrics"), make_groups(16), mesh=mesh8()
        ).run(max_ticks=64)
        np.testing.assert_array_equal(res1["status"], res8["status"])
        np.testing.assert_array_equal(
            res1["states"][0]["counter"], res8["states"][0]["counter"]
        )


class TestPingPong:
    def test_two_instance_rtt_windows(self):
        """pingpong.go:185-195: RTT ∈ [200,215]ms shaped at 100ms, then
        ∈ [20,35]ms after reconfiguring to 10ms — exact in sim time."""
        prog = SimProgram(
            plan_case("network", "ping-pong"),
            make_groups(
                2,
                params={
                    "latency_ms": "100",
                    "latency2_ms": "10",
                    "tolerance_ms": "15",
                },
            ),
            tick_ms=1.0,
            chunk=64,
        )
        res = prog.run(max_ticks=2048)
        assert (res["status"] == SUCCESS).all(), res["states"][0]
        rtt1 = np.asarray(res["states"][0]["rtt1"])
        rtt2 = np.asarray(res["states"][0]["rtt2"])
        assert ((rtt1 >= 200) & (rtt1 <= 215)).all(), rtt1
        assert ((rtt2 >= 20) & (rtt2 <= 35)).all(), rtt2

    def test_many_pairs_sharded(self):
        """16 independent pairs across the 8-device mesh."""
        prog = SimProgram(
            plan_case("network", "ping-pong"),
            make_groups(32),
            mesh=mesh8(),
            chunk=64,
        )
        res = prog.run(max_ticks=2048)
        assert (res["status"] == SUCCESS).all()

    def test_odd_instance_count_completes(self):
        """With an odd N the unpaired last instance must self-succeed
        instead of stalling the half-done barrier for the whole cohort
        (its partner index n is out of range and bounds-dropped)."""
        prog = SimProgram(
            plan_case("network", "ping-pong"),
            make_groups(3),
            chunk=64,
        )
        res = prog.run(max_ticks=2048)
        assert (res["status"] == SUCCESS).all(), res["status"]
        # the real pair still measured an RTT; the solo instance did not
        rtt1 = np.asarray(res["states"][0]["rtt1"])
        assert (rtt1[:2] > 0).all() and rtt1[2] == -1, rtt1

    def test_sustained_odd_instance_count(self):
        """pingpong-sustained judges the unpaired instance SUCCESS at the
        deadline rather than FAILURE with zero rounds."""
        prog = SimProgram(
            plan_case("network", "pingpong-sustained"),
            make_groups(3, params={"duration_ticks": "64"}),
            chunk=32,
        )
        res = prog.run(max_ticks=256)
        assert (res["status"] == SUCCESS).all(), res["status"]
        rounds = np.asarray(res["states"][0]["rounds"])
        assert (rounds[:2] > 0).all() and rounds[2] == 0, rounds

    def test_wrong_window_fails(self):
        """Tight tolerance ⇒ the assertion must fail (placebo for the
        RTT check itself)."""
        prog = SimProgram(
            plan_case("network", "ping-pong"),
            make_groups(2, params={"tolerance_ms": "-1"}),
            chunk=64,
        )
        res = prog.run(max_ticks=2048)
        assert (res["status"] == FAILURE).all()


class TestTraffic:
    def test_allowed_flows(self):
        prog = SimProgram(
            plan_case("network", "traffic-allowed"), make_groups(4), chunk=16
        )
        res = prog.run(max_ticks=256)
        assert (res["status"] == SUCCESS).all()
        assert (np.asarray(res["states"][0]["received"]) > 0).all()

    def test_blocked_does_not_flow(self):
        """splitbrain-style drop filter: no traffic crosses (09-11
        integration scripts' assertion)."""
        prog = SimProgram(
            plan_case("network", "traffic-blocked"), make_groups(4), chunk=16
        )
        res = prog.run(max_ticks=256)
        assert (res["status"] == SUCCESS).all()
        assert (np.asarray(res["states"][0]["received"]) == 0).all()


class TestTrafficShaped:
    """network/traffic-shaped: HTB bandwidth through a PLAN — the case
    itself asserts conservation (every burst message arrives) and exact
    per-tick pacing in sim time (``link.go:155-183`` semantics)."""

    def _run(self, instances, params, mesh=None):
        from testground_tpu.sim.executor import instantiate_testcase

        factory = load_sim_testcases(os.path.join(PLANS, "network"))[
            "traffic-shaped"
        ]
        groups = make_groups(instances, params=params)
        tc = instantiate_testcase(factory, groups, 1.0)
        return SimProgram(tc, groups, chunk=16, mesh=mesh).run(
            max_ticks=256
        )

    def test_burst_is_paced_and_conserved(self):
        res = self._run(4, {"burst": "8", "rate": "2"})
        assert (res["status"] == SUCCESS).all(), res["status"]
        assert res["bw_queue_dropped"] == 0
        # arrivals really were spread: last tick = send + 1 + floor(7/2)
        last = np.asarray(res["states"][0]["last_arrival"])
        sent = np.asarray(res["states"][0]["sent_at"])
        assert (last - sent == 4).all()

    def test_sub_one_msg_per_tick_rate_delivers(self):
        """rate 0.5 (below one message per tick) — the configuration the
        old admission-cap semantics turned into a blackhole — trickles
        every message through, 1 per 2 ticks."""
        res = self._run(2, {"burst": "4", "rate": "0.5"})
        assert (res["status"] == SUCCESS).all(), res["status"]
        last = np.asarray(res["states"][0]["last_arrival"])
        sent = np.asarray(res["states"][0]["sent_at"])
        assert (last - sent == 1 + 6).all()  # floor(3/0.5) = 6

    def test_sharded_matches_unsharded(self):
        params = {"burst": "6", "rate": "1.5"}
        res_s = self._run(16, params, mesh=mesh8())
        res_u = self._run(16, params)
        assert (res_s["status"] == SUCCESS).all()
        for k in ("received", "last_arrival", "sent_at"):
            np.testing.assert_array_equal(
                np.asarray(res_s["states"][0][k]),
                np.asarray(res_u["states"][0][k]),
            )


class TestMultiGroup:
    def test_heterogeneous_group_params(self):
        """Groups carry different static params — the trickle-down group
        merge surface (composition_preparation.go:232-281) feeding per-group
        vmaps."""

        class ParamEcho(SimTestcase):
            def init(self, env):
                return {"x": jnp.int32(env.int_param("x"))}

            def step(self, env, state, inbox, sync, t):
                return self.out(state, status=SUCCESS)

        groups = build_groups(
            [
                RunGroup(id="a", instances=2, parameters={"x": "7"}),
                RunGroup(id="b", instances=3, parameters={"x": "9"}),
            ]
        )
        res = SimProgram(ParamEcho(), groups).run(max_ticks=8)
        assert (np.asarray(res["states"][0]["x"]) == 7).all()
        assert (np.asarray(res["states"][1]["x"]) == 9).all()
        assert (res["status"] == SUCCESS).all()

    def test_cross_group_messaging(self):
        """Group a sends to group b via global indices; b succeeds on
        receipt, a on send."""

        class Sender(SimTestcase):
            MSG_WIDTH = 2

            def step(self, env, state, inbox, sync, t):
                dst = env.group_offset_of("b") + env.group_seq
                from testground_tpu.sim.api import Outbox

                return self.out(
                    state,
                    status=jnp.where(t >= 1, SUCCESS, RUNNING),
                    outbox=Outbox.single(
                        dst, jnp.asarray([5, 0]), t == 0, 1, 2
                    ),
                )

        class Receiver(SimTestcase):
            MSG_WIDTH = 2

            def step(self, env, state, inbox, sync, t):
                got = jnp.any(inbox.valid & (inbox.payload[0] == 5))
                return self.out(
                    state, status=jnp.where(got, SUCCESS, RUNNING)
                )

        class Dispatch(SimTestcase):
            MSG_WIDTH = 2

            def __init__(self):
                self._s, self._r = Sender(), Receiver()

            def init(self, env):
                return {}

            def step(self, env, state, inbox, sync, t):
                if env.group.id == "a":  # static per-group dispatch
                    return self._s.step(env, state, inbox, sync, t)
                return self._r.step(env, state, inbox, sync, t)

        groups = build_groups(
            [
                RunGroup(id="a", instances=3, parameters={}),
                RunGroup(id="b", instances=3, parameters={}),
            ]
        )
        res = SimProgram(Dispatch(), groups, chunk=8).run(max_ticks=64)
        assert (res["status"] == SUCCESS).all()


class TestTransportDiagnostics:
    """Engine plumbing for the NetFeedback counters: horizon clamps and
    HTB backlog thread through the tick loop and surface in results."""

    def test_htb_backlog_persists_across_ticks(self):
        """bandwidth_queue end-to-end: 4 sends at 0.5 msg/tick arrive
        every 2 ticks — the backlog state must survive apply_net_updates
        between ticks."""
        from testground_tpu.sim.api import Outbox

        class SlowLink(SimTestcase):
            SHAPING = ("latency", "bandwidth_queue")
            MSG_WIDTH = 1
            IN_MSGS = 2
            MAX_LINK_TICKS = 32
            # 0.5 msg/tick at 1 ms ticks
            DEFAULT_LINK = (1.0, 0.0, 0.5 * 256.0 * 1000.0, 0, 0, 0, 0)

            def init(self, env):
                return {
                    "got": jnp.int32(0),
                    "last_arrival": jnp.int32(-1),
                }

            def step(self, env, state, inbox, sync, t):
                is_sender = env.global_seq == 0
                got = state["got"] + inbox.count
                last = jnp.where(
                    inbox.count > 0, t, state["last_arrival"]
                )
                # sender emits one message per tick for ticks 0..3
                ob = Outbox.single(1, jnp.asarray([1]), (t < 4) & is_sender, 1, 1)
                done_send = is_sender & (t >= 10)
                done_recv = (env.global_seq == 1) & (t >= 10) & (got == 4)
                return self.out(
                    {"got": got, "last_arrival": last},
                    status=jnp.where(
                        done_send | done_recv, SUCCESS, RUNNING
                    ),
                    outbox=ob,
                )

        res = SimProgram(
            SlowLink(), make_groups(2), chunk=8
        ).run(max_ticks=64)
        assert (res["status"] == SUCCESS).all()
        # arrivals at ticks 1,3,5,7: the last one lands at tick 7
        assert int(res["states"][0]["last_arrival"][1]) == 7
        assert res["bw_queue_dropped"] == 0
        assert res["latency_clamped"] == 0

    def test_horizon_clamp_surfaces_in_results(self):
        """A mid-run net_shape latency past MAX_LINK_TICKS·tick_ms gets a
        visible count, not a silent speedup (VERDICT r3 weak #1)."""
        from testground_tpu.sim.api import Outbox

        class Overflow(SimTestcase):
            SHAPING = ("latency",)
            MSG_WIDTH = 1
            MAX_LINK_TICKS = 8

            def step(self, env, state, inbox, sync, t):
                # everyone reshapes to 50 ms latency at tick 0 (>> 7-tick
                # horizon), then instance 0 sends one message at tick 1
                ob = Outbox.single(
                    1, jnp.asarray([1]), (t == 1) & (env.global_seq == 0), 1, 1
                )
                return self.out(
                    state,
                    status=jnp.where(t >= 3, SUCCESS, RUNNING),
                    outbox=ob,
                    net_shape=self.link_shape(latency_ms=50.0),
                    net_shape_valid=t == 0,
                )

        res = SimProgram(Overflow(), make_groups(2), chunk=4).run(
            max_ticks=16
        )
        assert res["latency_clamped"] == 1

    def test_default_link_must_fit_horizon(self):
        """Static build check: an undeliverable DEFAULT_LINK fails at
        program construction, not silently at runtime."""

        class Bad(SimTestcase):
            MAX_LINK_TICKS = 8
            DEFAULT_LINK = (300.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0)

            def step(self, env, state, inbox, sync, t):
                return self.out(state, status=SUCCESS)

        with pytest.raises(ValueError, match="exceeds the calendar horizon"):
            SimProgram(Bad(), make_groups(2))

    def test_bandwidth_semantics_are_exclusive(self):
        class Both(SimTestcase):
            SHAPING = ("latency", "bandwidth", "bandwidth_queue")

            def step(self, env, state, inbox, sync, t):
                return self.out(state, status=SUCCESS)

        with pytest.raises(ValueError, match="not both"):
            SimProgram(Both(), make_groups(2))

    def test_queue_incompatible_with_direct_and_duplicate(self):
        """Deferral breaks direct mode's one-writer contract, and
        duplicate copies would bypass queue metering — both rejected
        statically instead of corrupting/overshooting silently."""

        class QDirect(SimTestcase):
            SHAPING = ("latency", "bandwidth_queue")
            SLOT_MODE = "direct"

            def step(self, env, state, inbox, sync, t):
                return self.out(state, status=SUCCESS)

        with pytest.raises(ValueError, match="direct"):
            SimProgram(QDirect(), make_groups(2))

        class QDup(SimTestcase):
            SHAPING = ("latency", "bandwidth_queue", "duplicate")

            def step(self, env, state, inbox, sync, t):
                return self.out(state, status=SUCCESS)

        with pytest.raises(ValueError, match="duplicate"):
            SimProgram(QDup(), make_groups(2))

    def test_undeclared_jitter_not_in_horizon_check(self):
        """DEFAULT_LINK jitter only counts against the horizon when the
        plan actually compiles jitter in — the plane is dead otherwise."""

        class NoJit(SimTestcase):
            SHAPING = ("latency",)
            MAX_LINK_TICKS = 8
            DEFAULT_LINK = (2.0, 500.0, 0.0, 0.0, 0.0, 0.0, 0.0)

            def step(self, env, state, inbox, sync, t):
                return self.out(state, status=SUCCESS)

        SimProgram(NoJit(), make_groups(2))  # must not raise

    def test_per_instance_filter_granularity(self):
        """The N_REGIONS = N escape hatch: region == instance gives full
        per-(src, dst) rule granularity — the tensor analog of the
        reference's arbitrarily-many per-subnet routes
        (``link.go:187-217``). Each instance drops exactly its right
        neighbor's traffic; every other pair flows."""
        from testground_tpu.sim.api import FILTER_ACCEPT, FILTER_DROP, Outbox

        N = 8

        class PerInstance(SimTestcase):
            SHAPING = ("latency", "filters")
            N_REGIONS = N
            MSG_WIDTH = 1
            OUT_MSGS = 1
            IN_MSGS = N

            def init(self, env):
                return {"got_from": jnp.zeros((N,), jnp.int32)}

            def step(self, env, state, inbox, sync, t):
                i = env.global_seq
                # t=0: claim region = my instance id and install MY rule
                # row: DROP toward region (i+1) % N, ACCEPT elsewhere
                rules = jnp.where(
                    jnp.arange(N) == jnp.mod(i + 1, N),
                    FILTER_DROP,
                    FILTER_ACCEPT,
                )
                # t=2..: send to every peer, one per tick (dst cycles)
                dst = jnp.mod(i + t, N)
                ob = Outbox.single(
                    dst, jnp.asarray([1]), (t >= 2) & (t < 2 + N), 1, 1
                )
                got = state["got_from"].at[inbox.src].add(
                    inbox.valid.astype(jnp.int32), mode="drop"
                )
                return self.out(
                    {"got_from": got},
                    status=jnp.where(t >= 2 + N + 4, SUCCESS, RUNNING),
                    outbox=ob,
                    region=i,
                    region_valid=t == 0,
                    net_filters=rules,
                    net_filters_valid=t == 0,
                )

        res = SimProgram(PerInstance(), make_groups(N), chunk=8).run(
            max_ticks=32
        )
        assert (res["status"] == SUCCESS).all()
        got = np.asarray(res["states"][0]["got_from"])  # [dst, src]
        for src in range(N):
            for dst in range(N):
                if src == dst:
                    continue
                expect = 0 if dst == (src + 1) % N else 1
                assert got[dst, src] == expect, (src, dst, got[dst, src])

    def test_direct_collision_detected_under_validate(self):
        """A colliding direct-mode plan reports the conflict via results
        when validate is on, and runs as today without (VERDICT r3 weak
        #3)."""
        from testground_tpu.sim.api import Outbox

        class Collide(SimTestcase):
            SHAPING = ("latency",)
            SLOT_MODE = "direct"
            MSG_WIDTH = 1
            OUT_MSGS = 1
            IN_MSGS = 2

            def step(self, env, state, inbox, sync, t):
                # every instance sends to instance 0, outbox slot 0 — a
                # deliberate fan-in violation of the direct contract
                ob = Outbox.single(0, jnp.asarray([1]), t == 0, 1, 1)
                return self.out(
                    state,
                    status=jnp.where(t >= 2, SUCCESS, RUNNING),
                    outbox=ob,
                )

        res = SimProgram(
            Collide(), make_groups(3), chunk=4, validate=True
        ).run(max_ticks=8)
        assert res["collisions"] == 2  # 3 senders, 1 slot: 2 conflicts
        assert res["collision_where"] == [0, 0]

        res2 = SimProgram(Collide(), make_groups(3), chunk=4).run(
            max_ticks=8
        )
        assert res2["collisions"] == 0


class TestTelemetryTotals:
    """The always-on observability floor: cumulative message-flow totals
    in results() — maintained whether or not the per-tick telemetry
    block is compiled in (that block's tests live in
    tests/test_sim_telemetry.py)."""

    def test_totals_without_telemetry_program(self):
        prog = SimProgram(
            plan_case("network", "ping-pong"), make_groups(4), chunk=16
        )
        res = prog.run(max_ticks=512)
        assert (res["status"] == SUCCESS).all()
        # 2 pairs × (ping+pong) × 2 latency phases = 16 messages
        assert res["msgs_sent"] == 16
        assert res["msgs_enqueued"] == 16
        assert res["msgs_delivered"] == 16
        assert res["msgs_dropped"] == 0
        assert res["msgs_rejected"] == 0
        assert res["cal_depth"] == 0
        assert res["carry_bytes"] == prog.estimate_carry_bytes()

    def test_conservation_under_lossy_links(self):
        """Under 50% loss the exact counts are draw-dependent, but the
        conservation law is not: sent = enqueued + dropped + rejected,
        and everything enqueued either delivered or is still in flight."""
        from testground_tpu.sim.api import Outbox

        class Lossy(SimTestcase):
            SHAPING = ("latency", "loss")
            MSG_WIDTH = 1
            IN_MSGS = 4
            MAX_LINK_TICKS = 8
            DEFAULT_LINK = (1.0, 0.0, 0.0, 50.0, 0.0, 0.0, 0.0)

            def step(self, env, state, inbox, sync, t):
                dst = jnp.mod(env.global_seq + 1, 8)
                ob = Outbox.single(dst, jnp.asarray([1]), t < 8, 1, 1)
                return self.out(
                    state,
                    status=jnp.where(t >= 12, SUCCESS, RUNNING),
                    outbox=ob,
                )

        res = SimProgram(
            Lossy(), make_groups(8), chunk=8
        ).run(max_ticks=32)
        assert res["msgs_sent"] == 8 * 8
        assert 0 < res["msgs_dropped"] < res["msgs_sent"]  # loss really hit
        assert (
            res["msgs_sent"]
            == res["msgs_enqueued"] + res["msgs_dropped"] + res["msgs_rejected"]
        )
        assert (
            res["msgs_enqueued"] - res["msgs_delivered"] == res["cal_depth"]
        )

    def test_reject_totals_match_feedback(self):
        """REJECT filters land in msgs_rejected (and only there): the
        dense-filter reject scenario from TestFilterRules, totalled."""
        from testground_tpu.sim.api import FILTER_REJECT, Outbox

        class Rejecting(SimTestcase):
            SHAPING = ("latency", "filters")
            MSG_WIDTH = 1
            IN_MSGS = 4
            MAX_LINK_TICKS = 8

            def step(self, env, state, inbox, sync, t):
                is_sender = env.global_seq == 0
                ob = Outbox.single(
                    1, jnp.asarray([1]), (t < 4) & is_sender, 1, 1
                )
                # group 0 rejects everything toward group 0 (the only
                # region) from tick 0 — every send suppressed
                return self.out(
                    state,
                    status=jnp.where(t >= 6, SUCCESS, RUNNING),
                    outbox=ob,
                    net_filters=jnp.asarray([FILTER_REJECT]),
                    net_filters_valid=t == 0,
                )

        res = SimProgram(Rejecting(), make_groups(2), chunk=8).run(
            max_ticks=32
        )
        # the tick-0 send precedes the filter application; ticks 1-3 reject
        assert res["msgs_sent"] == 4
        assert res["msgs_rejected"] == 3
        assert res["msgs_delivered"] == 1
        assert res["msgs_dropped"] == 0


class TestFilterTableBudget:
    def test_oversized_region_table_refused_statically(self):
        """VERDICT r4 #3: N_REGIONS = N at large N would allocate an
        O(N^2) filter table (40 GB at 100k) and die as an opaque XLA
        allocator error mid-trace; the program build must refuse with a
        readable message BEFORE any tracing or allocation."""

        class HugeRegions(SimTestcase):
            SHAPING = ("latency",)
            N_REGIONS = 1 << 15
            MSG_WIDTH = 1
            MAX_LINK_TICKS = 8

            def step(self, env, state, inbox, sync, t):
                return self.out(state, status=SUCCESS)

        with pytest.raises(ValueError, match="MAX_FILTER_CELLS"):
            SimProgram(HugeRegions(), make_groups(1 << 14), chunk=8)

    def test_documented_parity_scale_is_under_budget(self):
        """The ~8k per-instance-granularity parity bound (PERF.md) must
        construct fine — the budget guards allocation, not the perf
        envelope."""

        class PerInstance(SimTestcase):
            SHAPING = ("latency",)
            N_REGIONS = 8192
            MSG_WIDTH = 1
            MAX_LINK_TICKS = 8

            def step(self, env, state, inbox, sync, t):
                return self.out(state, status=SUCCESS)

        SimProgram(PerInstance(), make_groups(8192), chunk=8)


class TestFilterRules:
    """Per-instance RANGE-RULE filters ("filter_rules") — the scalable
    granularity model (O(N·K), any instance count) beside the dense
    [R, N] region table (VERDICT r4 #3 strong option): iptables-style
    first-match rule lists per instance over dst index ranges, the
    tensor analog of the reference sidecar's per-instance subnet rules
    (link.go:187-217)."""

    def _ruled(self, send_tick=2, set_tick=0, rules_of=None):
        from testground_tpu.sim.api import (
            FILTER_DROP,
            FILTER_REJECT,
            Outbox,
        )

        class Ruled(SimTestcase):
            SHAPING = ("latency", "filter_rules")
            FILTER_RULES = 2
            MSG_WIDTH = 1
            OUT_MSGS = 3
            IN_MSGS = 4
            MAX_LINK_TICKS = 8

            def init(self, env):
                return {
                    "got": jnp.int32(0),
                    "rejected": jnp.int32(0),
                }

            def step(self, env, state, inbox, sync, t):
                is_sender = env.global_seq == 0
                rules = (
                    rules_of(self)
                    if rules_of is not None
                    # dst 1 REJECTed (first match beats the wider DROP
                    # rule below), dst 2 DROPped, dst 3 untouched
                    else self.filter_rules(
                        (1, 2, FILTER_REJECT), (1, 3, FILTER_DROP)
                    )
                )
                ob = Outbox(
                    dst=jnp.asarray([1, 2, 3], jnp.int32),
                    payload=jnp.ones((3, 1), jnp.int32),
                    valid=jnp.full((3,), (t == send_tick) & is_sender, bool),
                )
                return self.out(
                    {
                        "got": state["got"] + inbox.count,
                        "rejected": state["rejected"] + sync.rejected,
                    },
                    status=jnp.where(t >= 6, SUCCESS, RUNNING),
                    outbox=ob,
                    net_rules=rules,
                    net_rules_valid=(t == set_tick) & is_sender,
                )

        return Ruled

    def test_first_match_accept_reject_drop(self):
        res = SimProgram(
            self._ruled()(), make_groups(4), chunk=4
        ).run(max_ticks=32)
        got = np.asarray(res["states"][0]["got"])
        # dst 1: REJECT (first match), dst 2: DROP, dst 3: accepted
        assert got.tolist()[1:] == [0, 0, 1]
        # exactly the REJECT fed back to the sender; DROP is silent
        assert int(np.asarray(res["states"][0]["rejected"])[0]) == 1

    def test_unset_rules_accept_everything(self):
        def no_rules(tc):
            return tc.filter_rules()

        res = SimProgram(
            self._ruled(rules_of=no_rules)(), make_groups(4), chunk=4
        ).run(max_ticks=32)
        got = np.asarray(res["states"][0]["got"])
        assert got.tolist()[1:] == [1, 1, 1]

    def test_dynamic_rule_update_applies_next_tick(self):
        """A rule list emitted at tick T shapes sends from T+1 on — the
        one-tick sidecar turnaround, same as net_shape/net_filters."""
        from testground_tpu.sim.api import FILTER_DROP, Outbox

        class Streamer(SimTestcase):
            SHAPING = ("latency", "filter_rules")
            FILTER_RULES = 1
            MSG_WIDTH = 1
            IN_MSGS = 2
            MAX_LINK_TICKS = 8

            def init(self, env):
                return {"got": jnp.int32(0), "last": jnp.int32(-1)}

            def step(self, env, state, inbox, sync, t):
                is_sender = env.global_seq == 0
                ob = Outbox.single(
                    1, jnp.asarray([1]), (t < 10) & is_sender, 1, 1
                )
                return self.out(
                    {
                        "got": state["got"] + inbox.count,
                        "last": jnp.where(
                            inbox.count > 0, t, state["last"]
                        ),
                    },
                    status=jnp.where(t >= 14, SUCCESS, RUNNING),
                    outbox=ob,
                    net_rules=self.filter_rules((1, 2, FILTER_DROP)),
                    net_rules_valid=(t == 4) & is_sender,
                )

        res = SimProgram(Streamer(), make_groups(2), chunk=4).run(
            max_ticks=32
        )
        st = res["states"][0]
        # sends at t=0..4 arrive t=1..5 (the t=4 send precedes the rule
        # application at tick 4's end); sends t>=5 are dropped
        assert int(np.asarray(st["got"])[1]) == 5
        assert int(np.asarray(st["last"])[1]) == 5

    def test_sharded_matches_unsharded(self):
        def run(mesh):
            return SimProgram(
                self._ruled()(), make_groups(8), chunk=4, mesh=mesh
            ).run(max_ticks=32)

        a, b = run(None), run(mesh8())
        for key in ("got", "rejected"):
            assert (
                np.asarray(a["states"][0][key])
                == np.asarray(b["states"][0][key])
            ).all(), key
        assert (a["status"] == b["status"]).all()

    def test_declaration_errors(self):
        class Both(SimTestcase):
            SHAPING = ("latency", "filters", "filter_rules")
            FILTER_RULES = 2

            def step(self, env, state, inbox, sync, t):
                return self.out(state)

        with pytest.raises(ValueError, match="not both"):
            SimProgram(Both(), make_groups(2), chunk=4)

        class NoK(SimTestcase):
            SHAPING = ("latency", "filter_rules")

            def step(self, env, state, inbox, sync, t):
                return self.out(state)

        with pytest.raises(ValueError, match="FILTER_RULES > 0"):
            SimProgram(NoK(), make_groups(2), chunk=4)


class TestFilterRulesComposition:
    def test_rejected_messages_consume_no_queue_service(self):
        """filter_rules composes with bandwidth_queue: filters apply
        BEFORE queue admission (as tc applies netfilter before HTB), so
        REJECTed messages neither occupy the egress queue nor delay the
        accepted traffic behind them."""
        from testground_tpu.sim.api import FILTER_REJECT, Outbox
        from testground_tpu.sim.net import MSG_BYTES

        class RuledQueue(SimTestcase):
            SHAPING = ("latency", "filter_rules", "bandwidth_queue")
            FILTER_RULES = 1
            MSG_WIDTH = 1
            OUT_MSGS = 2
            IN_MSGS = 4
            MAX_LINK_TICKS = 16
            # 1 msg/tick service rate at 1 ms ticks
            DEFAULT_LINK = (1.0, 0.0, 1.0 * MSG_BYTES * 1000.0, 0, 0, 0, 0)

            def init(self, env):
                return {
                    "got": jnp.int32(0),
                    "last": jnp.int32(-1),
                    "rejected": jnp.int32(0),
                }

            def step(self, env, state, inbox, sync, t):
                # instance 0 sends a (blocked-to-1, allowed-to-2) pair
                # per tick for 4 ticks; the rule blocks dst 1 from the
                # start, so dst 2's traffic must pace at the FULL rate —
                # 1 msg/tick, arrivals t+1 — as if dst 1's never existed
                is_sender = env.global_seq == 0
                send = (t >= 1) & (t < 5) & is_sender
                ob = Outbox(
                    dst=jnp.asarray([1, 2], jnp.int32),
                    payload=jnp.ones((2, 1), jnp.int32),
                    valid=jnp.full((2,), send, bool),
                )
                return self.out(
                    {
                        "got": state["got"] + inbox.count,
                        "last": jnp.where(
                            inbox.count > 0, t, state["last"]
                        ),
                        "rejected": state["rejected"] + sync.rejected,
                    },
                    status=jnp.where(t >= 12, SUCCESS, RUNNING),
                    outbox=ob,
                    net_rules=self.filter_rules((1, 2, FILTER_REJECT)),
                    net_rules_valid=(t == 0) & is_sender,
                )

        res = SimProgram(RuledQueue(), make_groups(3), chunk=8).run(
            max_ticks=32
        )
        st = res["states"][0]
        assert np.asarray(st["got"]).tolist() == [0, 0, 4]
        # accepted stream rides the full 1 msg/tick rate: last arrival
        # t=5 (send t=4 + latency 1) — a reject that consumed service
        # would push it later
        assert int(np.asarray(st["last"])[2]) == 5
        assert int(np.asarray(st["rejected"])[0]) == 4
        assert res["bw_queue_dropped"] == 0
