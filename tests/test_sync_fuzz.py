"""Property-based fuzz of the on-device sync kernel against a plain
Python model of the reference sync service's semantics (SURVEY.md §2.6):
atomic counters with deterministic same-tick ranking, bounded append-only
topic streams with per-instance cursors, and overflow accounting."""

import jax.numpy as jnp
import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property-based tier needs hypothesis"
)
from hypothesis import given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from testground_tpu.sim.sync_kernel import (
    make_sub_window,
    make_sync_state,
    update_sync,
)


@st.composite
def schedules(draw):
    n = draw(st.integers(1, 8))
    n_states = draw(st.integers(1, 3))
    n_topics = draw(st.integers(0, 3))
    cap = draw(st.sampled_from([2, 4, 8]))
    pw = draw(st.integers(1, 3))
    sub_k = draw(st.integers(1, 4))
    ticks = draw(st.integers(1, 6))
    rng = np.random.default_rng(draw(st.integers(0, 2**31 - 1)))
    steps = []
    for _ in range(ticks):
        steps.append(
            dict(
                signals=rng.integers(0, 2, (n_states, n)).astype(np.int32),
                pub_valid=rng.random((max(n_topics, 1), n)) < 0.5,
                pub_payload=rng.integers(
                    1, 1000, (max(n_topics, 1), pw, n)
                ).astype(np.int32),
                sub_consume=rng.integers(
                    0, sub_k + 1, (max(n_topics, 1), n)
                ).astype(np.int32),
            )
        )
    return dict(
        n=n, n_states=n_states, n_topics=n_topics, cap=cap, pw=pw,
        sub_k=sub_k, steps=steps,
    )


class Model:
    """The reference semantics, written the obvious sequential way."""

    def __init__(self, n, n_states, n_topics, cap):
        self.counts = [0] * n_states
        self.last_seq = [[0] * n for _ in range(n_states)]
        self.streams = [[] for _ in range(n_topics)]  # payload rows
        self.cursors = [[0] * n for _ in range(n_topics)]
        self.dropped = [0] * n_topics
        self.cap = cap
        self.n = n

    def step(self, signals, pub_valid, pub_payload, sub_consume):
        for s, row in enumerate(signals):
            rank = 0
            for i in range(self.n):
                if row[i]:
                    rank += 1
                    self.last_seq[s][i] = self.counts[s] + rank
            self.counts[s] += rank
        for t in range(len(self.streams)):
            for i in range(self.n):  # publish in instance order
                if pub_valid[t][i]:
                    if len(self.streams[t]) < self.cap:
                        self.streams[t].append(
                            [int(w) for w in pub_payload[t, :, i]]
                        )
                    else:
                        self.dropped[t] += 1
            for i in range(self.n):
                self.cursors[t][i] = min(
                    self.cursors[t][i] + max(int(sub_consume[t][i]), 0),
                    len(self.streams[t]),
                )

    def window(self, sub_k):
        """Expected (entries, valid[N,T,K]) like make_sub_window."""
        T = len(self.streams)
        out_valid = np.zeros((self.n, T, sub_k), dtype=bool)
        entries = []
        for i in range(self.n):
            for t in range(T):
                for k in range(sub_k):
                    pos = self.cursors[t][i] + k
                    ok = pos < len(self.streams[t])
                    out_valid[i, t, k] = ok
                    if ok:
                        entries.append((i, t, k, self.streams[t][pos]))
        return entries, out_valid


@settings(max_examples=40, deadline=None)
@given(schedules())
def test_sync_kernel_matches_reference_model(sched):
    n, n_states, n_topics = sched["n"], sched["n_states"], sched["n_topics"]
    cap, pw, sub_k = sched["cap"], sched["pw"], sched["sub_k"]
    sync = make_sync_state(n, n_states, n_topics, cap, pw)
    model = Model(n, n_states, n_topics, cap)

    for step in sched["steps"]:
        sig = jnp.asarray(step["signals"])
        pv = jnp.asarray(step["pub_valid"])[:n_topics]
        pp = jnp.asarray(step["pub_payload"])[:n_topics]
        sc = jnp.asarray(step["sub_consume"])[:n_topics]
        sync = update_sync(sync, sig, pp, pv, sc)
        model.step(step["signals"], step["pub_valid"], step["pub_payload"],
                   step["sub_consume"])

        assert np.asarray(sync.counts).tolist() == model.counts
        assert np.asarray(sync.last_seq).tolist() == model.last_seq
        if n_topics:
            assert (
                np.asarray(sync.stream_len).tolist()
                == [len(s) for s in model.streams]
            )
            assert np.asarray(sync.dropped).tolist() == model.dropped
            assert np.asarray(sync.cursors).tolist() == model.cursors
            # stored stream contents equal, in publish order
            stream = np.asarray(sync.stream)
            for t, entries in enumerate(model.streams):
                for pos, payload in enumerate(entries):
                    assert stream[t, pos].tolist() == payload

            entries, valid = model.window(sub_k)
            sub_pay, sub_valid = make_sub_window(sync, sub_k)
            assert np.array_equal(np.asarray(sub_valid), valid)
            sub_pay = np.asarray(sub_pay)
            for i, t, k, payload in entries:
                assert sub_pay[i, t, k].tolist() == payload
