"""Daemon + client tests: the same CLI verbs that work in-process must
work against a daemon over HTTP (SURVEY.md §4 tier 3 — the analog of
``pkg/integration/utils/daemon.go:13-36`` in-process daemon tests),
including bearer-token auth (``daemon.go:49-70``)."""

import io
import os
import tarfile
import time

import pytest

from testground_tpu.client import Client, DaemonError
from testground_tpu.config import EnvConfig
from testground_tpu.daemon import Daemon

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


@pytest.fixture()
def daemon(tg_home):
    d = Daemon(env=EnvConfig.load(), listen="localhost:0")
    d.start()
    yield d
    d.stop()


@pytest.fixture()
def client(daemon):
    return Client(daemon.address)


def _placebo_composition(case="ok", instances=2):
    return {
        "metadata": {"name": f"placebo-{case}"},
        "global": {
            "plan": "placebo",
            "case": case,
            "builder": "exec:py",
            "runner": "local:exec",
            "total_instances": instances,
        },
        "groups": [
            {"id": "all", "instances": {"count": instances}},
        ],
    }


def _wait(client, task_id, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        t = client.status(task_id)
        if t["states"][-1]["state"] in ("complete", "canceled"):
            return t
        time.sleep(0.2)
    raise TimeoutError(task_id)


class TestDaemonEndToEnd:
    def test_import_run_logs_outputs(self, client):
        # plan import over HTTP (tar.gz body)
        assert client.import_plan(os.path.join(PLANS, "placebo")) == "placebo"

        task_id = client.run(_placebo_composition())
        t = _wait(client, task_id)
        assert t["outcome"] == "success"

        # logs stream the task's chunk lines
        lines = list(client.logs(task_id))
        assert any('"t":' in ln or ln.strip() for ln in lines)

        # task listing includes it
        ids = [d["id"] for d in client.tasks()]
        assert task_id in ids

        # outputs tgz contains per-instance run.out files
        buf = io.BytesIO()
        client.collect_outputs("local:exec", task_id, buf)
        buf.seek(0)
        with tarfile.open(fileobj=buf, mode="r:gz") as tar:
            names = tar.getnames()
        assert any(name.endswith("run.out") for name in names)

    def test_run_unknown_plan_404s(self, client):
        with pytest.raises(DaemonError, match="not found on the daemon"):
            client.run(_placebo_composition())

    def test_healthcheck_and_kill(self, client):
        client.import_plan(os.path.join(PLANS, "placebo"))
        report, _ = client.healthcheck("local:exec", fix=True)
        names = {c.name for c in report.checks}
        assert "outputs-dir-writable" in names
        assert "sync-service-port-bindable" in names
        assert report.ok()
        # kill an un-poppable task id → killed=False
        assert client.kill("nonexistent") is False


    def test_terminate_runner_and_param_validation(self, client, daemon):
        """POST /terminate takes runner OR builder; an empty body is a
        clean 400, not a 500 (terminate.go:38-45)."""
        import json as _json
        import urllib.error
        from urllib.request import Request, urlopen

        out = client.terminate(runner="local:exec")
        assert "all jobs terminated" in out
        req = Request(
            f"{daemon.address}/terminate",
            data=_json.dumps({}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urlopen(req)
        assert ei.value.code == 400

    def test_status_unknown_task(self, client):
        with pytest.raises(DaemonError):
            client.status("missing-task")

    def test_kill_delete_also_served_on_get(self, client, daemon):
        """The reference serves kill/delete as GET routes (daemon.go:87-88,
        dashboard links); both verbs answer on GET with query params."""
        import json as _json
        from urllib.request import urlopen

        client.import_plan(os.path.join(PLANS, "placebo"))
        task_id = client.run(_placebo_composition())
        _wait(client, task_id)
        base = daemon.address
        with urlopen(f"{base}/kill?task_id={task_id}") as r:
            assert _json.load(r) == {"killed": False}  # already finished
        with urlopen(f"{base}/delete?task_id={task_id}") as r:
            assert _json.load(r) == {"deleted": True}

    def test_describe_plan_remote(self, client):
        """GET /describe serves the daemon-side manifest so a remote CLI
        can run daemon-hosted plans with no local copy."""
        client.import_plan(os.path.join(PLANS, "placebo"))
        m = client.describe_plan("placebo")
        assert m.name == "placebo"
        assert m.testcase_by_name("ok") is not None
        with pytest.raises(DaemonError, match="not found"):
            client.describe_plan("nope")
        with pytest.raises(DaemonError, match="invalid plan name"):
            client.describe_plan("../etc")

    def test_run_single_remote_without_local_plan(
        self, daemon, tmp_path, monkeypatch, capsys
    ):
        """`tg run single` against a daemon must work when the plan exists
        ONLY on the daemon (manifest fetched via /describe)."""
        from testground_tpu.cli.main import main

        Client(daemon.address).import_plan(os.path.join(PLANS, "placebo"))
        # point the CLI at a fresh, empty home with no local plans
        clihome = tmp_path / "clihome"
        clihome.mkdir()
        monkeypatch.setenv("TESTGROUND_HOME", str(clihome))
        rc = main(
            [
                "--endpoint", daemon.address,
                "run", "single", "placebo:ok",
                "--builder", "exec:py", "--runner", "local:exec", "-i", "2",
            ]
        )
        assert rc == 0
        assert "outcome: success" in capsys.readouterr().out

    def test_delete_task(self, client):
        """GET /delete parity (``daemon.go:88``): a finished task's record
        and log are removed; a live/unknown task is refused/false."""
        client.import_plan(os.path.join(PLANS, "placebo"))
        # a live (stalling) task is refused with a 409 until killed
        live_id = client.run(_placebo_composition(case="stall"))
        with pytest.raises(DaemonError, match="kill it before deleting"):
            client.delete(live_id)
        client.kill(live_id)
        _wait(client, live_id)

        task_id = client.run(_placebo_composition())
        _wait(client, task_id)
        assert client.delete(task_id) is True
        with pytest.raises(DaemonError):  # record gone
            client.status(task_id)
        assert client.delete(task_id) is False  # idempotent-ish: now unknown

    def test_logs_unknown_task_is_clean_404(self, client):
        """The daemon must reject an unknown task id BEFORE starting the
        chunked stream, as a single well-formed error response."""
        with pytest.raises(DaemonError, match="unknown task"):
            list(client.logs("missing-task"))

    def test_runsless_composition_via_raw_client(self, client):
        """A composition without [[runs]] must work through the raw Client:
        the daemon synthesizes the default run server-side like the
        reference's PrepareForRun (composition_preparation.go:93-110)."""
        client.import_plan(os.path.join(PLANS, "placebo"))
        comp = _placebo_composition()
        assert "runs" not in comp
        task_id = client.run(comp)
        t = _wait(client, task_id)
        assert t["outcome"] == "success"


class TestPathTraversal:
    def test_run_rejects_traversal_plan_name(self, client):
        comp = _placebo_composition()
        comp["global"]["plan"] = "../outputs"
        with pytest.raises(DaemonError, match="invalid plan name"):
            client.run(comp)

    def test_plan_import_rejects_traversal_name(self, client, tg_home):
        victim = os.path.join(tg_home, "victim")
        os.makedirs(victim)
        open(os.path.join(victim, "keep.txt"), "w").close()
        with pytest.raises(DaemonError, match="invalid plan name"):
            client.import_plan(
                os.path.join(PLANS, "placebo"), name="../victim"
            )
        assert os.path.exists(os.path.join(victim, "keep.txt"))


class TestAuth:
    def test_token_required_when_configured(self, tg_home):
        env = EnvConfig.load()
        env.daemon.tokens = ["sekrit"]
        d = Daemon(env=env, listen="localhost:0")
        d.start()
        try:
            with pytest.raises(DaemonError, match="unauthorized"):
                Client(d.address).tasks()
            assert Client(d.address, token="sekrit").tasks() == []
        finally:
            d.stop()


class TestCLIAgainstDaemon:
    def test_cli_verbs_with_endpoint(self, daemon, tmp_path, capsys):
        """The same `tg` verbs, pointed at the daemon via --endpoint
        (the reference's client↔daemon hop)."""
        from testground_tpu.cli.main import main

        ep = daemon.address
        assert (
            main(
                [
                    "--endpoint", ep, "plan", "import",
                    "--from", os.path.join(PLANS, "placebo"),
                ]
            )
            == 0
        )

        comp_file = tmp_path / "comp.toml"
        comp_file.write_text(
            """
[metadata]
name = "placebo-ok"

[global]
plan = "placebo"
case = "ok"
builder = "exec:py"
runner = "local:exec"
total_instances = 2

[[groups]]
id = "all"

[groups.instances]
count = 2
"""
        )
        rc = main(
            ["--endpoint", ep, "run", "composition", "-f", str(comp_file)]
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert "run is queued with ID:" in out
        assert "outcome: success" in out

        task_id = out.split("run is queued with ID:")[1].split()[0]
        assert main(["--endpoint", ep, "status", "-t", task_id]) == 0
        assert "Outcome: success" in capsys.readouterr().out
        assert main(["--endpoint", ep, "tasks"]) == 0
        assert task_id in capsys.readouterr().out
        assert main(["--endpoint", ep, "logs", "-t", task_id]) == 0
        assert main(["--endpoint", ep, "healthcheck", "--runner", "local:exec"]) == 0

    def test_detach_queues_without_waiting(self, daemon, tmp_path, capsys):
        """`tg run composition --detach` against a daemon exits right
        after queueing (the reference's non---wait mode); the task then
        completes on the daemon and is queryable."""
        from testground_tpu.cli.main import main

        ep = daemon.address
        main(
            [
                "--endpoint", ep, "plan", "import",
                "--from", os.path.join(PLANS, "placebo"),
            ]
        )
        comp_file = tmp_path / "comp.toml"
        comp_file.write_text(
            "[metadata]\nname = \"detached\"\n\n"
            "[global]\nplan = \"placebo\"\ncase = \"ok\"\n"
            "builder = \"exec:py\"\nrunner = \"local:exec\"\n"
            "total_instances = 1\n\n"
            "[[groups]]\nid = \"all\"\n[groups.instances]\ncount = 1\n"
        )
        capsys.readouterr()
        rc = main(
            [
                "--endpoint", ep, "run", "composition",
                "-f", str(comp_file), "--detach",
            ]
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert "run is queued with ID:" in out
        assert "finished run" not in out  # did not wait
        task_id = out.split("run is queued with ID:")[1].split()[0]
        t = _wait(Client(ep), task_id)
        assert t["states"][-1]["state"] == "complete"


class TestGetRoutes:
    """GET /logs, /outputs, and the / redirect (daemon.go:85-91 serves
    these on GET for dashboard links)."""

    def test_get_logs_and_outputs(self, client, daemon):
        import io as _io
        import tarfile
        from urllib.request import urlopen

        client.import_plan(os.path.join(PLANS, "placebo"))
        task_id = client.run(_placebo_composition())
        _wait(client, task_id)
        base = daemon.address

        with urlopen(f"{base}/logs?task_id={task_id}") as r:
            body = r.read().decode()
        # the task log must be THIS run's: its own id appears in the lines
        assert task_id in body

        with urlopen(
            f"{base}/outputs?runner=local:exec&run_id={task_id}"
        ) as r:
            data = r.read()
        with tarfile.open(fileobj=_io.BytesIO(data), mode="r:gz") as tar:
            assert any("run.out" in n for n in tar.getnames())

    def test_dashboard_multi_run_outputs_links(self, client, daemon):
        """Multi-[[runs]] tasks store outputs under <task_id>-<run_id>
        dirs, so the dashboard must emit one outputs link per run — a
        bare task_id link would 404 (same gap as CLI --collect after a
        multi-run composition)."""
        from urllib.request import urlopen

        client.import_plan(os.path.join(PLANS, "placebo"))
        comp = _placebo_composition(instances=1)
        comp["runs"] = [
            {"id": "r_a", "groups": [{"id": "all", "instances": {"count": 1}}]},
            {"id": "r_b", "groups": [{"id": "all", "instances": {"count": 1}}]},
        ]
        task_id = client.run(comp)
        _wait(client, task_id)
        with urlopen(f"{daemon.address}/dashboard?task_id={task_id}") as r:
            html = r.read().decode()
        for rid in ("r_a", "r_b"):
            assert f"run_id={task_id}-{rid}" in html
        # and each linked tarball actually downloads
        with urlopen(
            f"{daemon.address}/outputs?runner=local:exec&run_id={task_id}-r_a"
        ) as r:
            assert r.read()[:2] == b"\x1f\x8b"  # gzip magic

    def test_get_logs_requires_task_id(self, daemon):
        import urllib.error
        from urllib.request import urlopen

        with pytest.raises(urllib.error.HTTPError) as ei:
            urlopen(f"{daemon.address}/logs")
        assert ei.value.code == 400

    def test_root_redirects_to_dashboard(self, daemon):
        from urllib.request import urlopen

        with urlopen(f"{daemon.address}/") as r:
            # urllib follows the 302; we land on the dashboard HTML
            assert r.url.endswith("/dashboard")


class TestConcurrentClients:
    def test_parallel_runs_from_many_clients(self, client, daemon):
        """Several clients queue runs at once; the daemon's engine drains
        them all with correct outcomes (ThreadingHTTPServer + engine locks
        under real concurrency)."""
        import concurrent.futures

        client.import_plan(os.path.join(PLANS, "placebo"))

        def one(i):
            c = Client(daemon.address)
            case = "ok" if i % 2 == 0 else "abort"
            tid = c.run(_placebo_composition(case=case, instances=1))
            t = _wait(c, tid, timeout=120)
            return case, t["result"]["outcome"] if t.get("result") else None

        with concurrent.futures.ThreadPoolExecutor(max_workers=6) as ex:
            results = list(ex.map(one, range(6)))
        for case, outcome in results:
            expected = "success" if case == "ok" else "failure"
            assert outcome == expected, (case, outcome)

    def test_get_outputs_rejects_traversal_run_id(self, daemon):
        """run_id must be a single path component — a traversal id would
        tar arbitrary host directories out through the open GET route."""
        import urllib.error
        from urllib.parse import quote
        from urllib.request import urlopen

        bad = quote("../../../../etc", safe="")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urlopen(
                f"{daemon.address}/outputs?runner=local:exec&run_id={bad}"
            )
        assert ei.value.code == 400

    def test_get_tasks_honors_query_filters(self, client, daemon):
        """GET /tasks applies before/after query params (the dashboard's
        GET surface must filter like POST does)."""
        import json as _json
        from urllib.request import urlopen

        client.import_plan(os.path.join(PLANS, "placebo"))
        tid = client.run(_placebo_composition(instances=1))
        _wait(client, tid)
        base = daemon.address
        with urlopen(f"{base}/tasks") as r:
            assert any(t["id"] == tid for t in _json.load(r)["tasks"])
        with urlopen(f"{base}/tasks?before=1000000000") as r:
            assert _json.load(r)["tasks"] == []

    def test_get_tasks_states_types_are_lists(self, client, daemon):
        """states/types query params are list filters, not substring
        matchers: repeated params all apply, and a state name that is a
        substring of nothing real ('comp') must match nothing."""
        import json as _json
        from urllib.request import urlopen

        client.import_plan(os.path.join(PLANS, "placebo"))
        tid = client.run(_placebo_composition(instances=1))
        _wait(client, tid)
        base = daemon.address
        with urlopen(f"{base}/tasks?states=complete&types=run") as r:
            assert any(t["id"] == tid for t in _json.load(r)["tasks"])
        # repeated values: either state matching suffices
        with urlopen(f"{base}/tasks?states=canceled&states=complete") as r:
            assert any(t["id"] == tid for t in _json.load(r)["tasks"])
        # a superstring of a real state is NOT a match (scalar strings
        # used to flow into storage.filter's `in` and substring-match:
        # 'complete' in 'completely' was True)
        with urlopen(f"{base}/tasks?states=completely") as r:
            assert _json.load(r)["tasks"] == []
