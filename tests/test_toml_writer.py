"""TOML emitter round-trip tests."""

from testground_tpu.utils.compat import tomllib

import pytest

from testground_tpu.utils.toml_writer import dumps


@pytest.mark.parametrize(
    "doc",
    [
        {"a": 1, "b": "x", "c": True, "d": 1.5},
        {"t": {"nested": {"k": "v"}}, "top": "x"},
        {"arr": [1, 2, 3], "sarr": ["a", "b"]},
        {"groups": [{"id": "a", "n": 1}, {"id": "b", "n": 2}]},
        {"s": 'quote " backslash \\ newline \n tab \t'},
        {"weird key.with dots": {"inner": 1}},
        {"global": {"run": {"test_params": {"k": "v"}}}},
        {"empty_list": [], "empty_table": {}},
    ],
)
def test_round_trip(doc):
    assert tomllib.loads(dumps(doc)) == doc


def test_rejects_unencodable():
    with pytest.raises(TypeError):
        dumps({"x": object()})
