"""End-to-end: composition → engine → sim:plan build → sim:jax run →
outcomes + outputs + collection (the integration-script tier of SURVEY.md §4
with the simulator as the substrate)."""

import io
import os
import tarfile
import time

import pytest

from testground_tpu.api import (
    Composition,
    Global,
    Group,
    Instances,
    TestPlanManifest,
    generate_default_run,
)
from testground_tpu.builders.sim_plan import SimPlanBuilder
from testground_tpu.config import EnvConfig
from testground_tpu.engine import Engine, EngineConfig, Outcome, State
from testground_tpu.sim.runner import SimJaxRunner

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


@pytest.fixture()
def engine(tg_home):
    env = EnvConfig.load()
    e = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    e.start_workers()
    yield e
    e.stop()


def run_sim(
    engine, plan, case, instances=2, params=None, run_params=None, timeout=180
):
    comp = generate_default_run(
        Composition(
            global_=Global(
                plan=plan, case=case, builder="sim:plan", runner="sim:jax"
            ),
            groups=[Group(id="all", instances=Instances(count=instances))],
        )
    )
    if params:
        comp.runs[0].groups[0].test_params.update(params)
    if run_params:
        comp.global_.run_config.update(run_params)
    manifest = TestPlanManifest.load_file(
        os.path.join(PLANS, plan, "manifest.toml")
    )
    tid = engine.queue_run(comp, manifest, sources_dir=os.path.join(PLANS, plan))
    deadline = time.time() + timeout
    while time.time() < deadline:
        t = engine.get_task(tid)
        if t is not None and t.state().state in (State.COMPLETE, State.CANCELED):
            return t
        time.sleep(0.05)
    raise TimeoutError(f"task {tid} did not finish")


@pytest.mark.slow  # ~30s (jax.profiler trace capture): past the tier-1
# 870s budget's ~20s per-test ceiling
class TestProfiles:
    def test_profile_capture_writes_trace(self, tg_home):
        """A group requesting profiles makes the run record a jax.profiler
        trace into the run outputs dir (the pprof analog,
        ``composition.go:153-162``)."""
        import threading

        from testground_tpu.api import RunGroup, RunInput
        from testground_tpu.rpc import discard_writer
        from testground_tpu.sim.executor import execute_sim_run

        env = EnvConfig.load()
        job = RunInput(
            run_id="profrun",
            test_plan="placebo",
            test_case="ok",
            total_instances=4,
            groups=[
                RunGroup(
                    id="all",
                    instances=4,
                    artifact_path=os.path.join(PLANS, "placebo"),
                    parameters={},
                    profiles={"cpu": "true"},
                )
            ],
            env=env,
        )
        out = execute_sim_run(job, discard_writer(), threading.Event())
        assert out.result.outcome == Outcome.SUCCESS
        pdir = os.path.join(
            env.dirs.outputs(), "placebo", "profrun", "profiles"
        )
        found = [f for _, _, fs in os.walk(pdir) for f in fs]
        assert any("trace" in f or f.endswith(".pb") for f in found), found


class TestSimPlacebo:
    def test_ok(self, engine):
        t = run_sim(engine, "placebo", "ok", instances=8)
        assert t.outcome() == Outcome.SUCCESS
        assert t.result["outcomes"]["all"] == {"total": 8, "ok": 8}

    def test_abort_fails(self, engine):
        t = run_sim(engine, "placebo", "abort", instances=2)
        assert t.outcome() == Outcome.FAILURE

    def test_stall_bounded_by_max_ticks(self, engine):
        t = run_sim(
            engine,
            "placebo",
            "stall",
            instances=2,
            run_params={"max_ticks": 64, "chunk": 16},
        )
        assert t.outcome() == Outcome.FAILURE
        assert t.result["journal"]["events"]["all"]["incomplete"] == 2

    def test_outputs_and_collection(self, engine):
        t = run_sim(engine, "placebo", "metrics", instances=2)
        out_root = engine.env.dirs.outputs()
        inst = os.path.join(out_root, "placebo", t.id, "all", "0")
        assert os.path.getsize(os.path.join(inst, "run.out")) > 0
        assert os.path.getsize(os.path.join(inst, "metrics.out")) > 0

        buf = io.BytesIO()
        from testground_tpu.rpc import discard_writer

        engine.do_collect_outputs("sim:jax", t.id, buf, discard_writer())
        buf.seek(0)
        with tarfile.open(fileobj=buf, mode="r:gz") as tar:
            names = tar.getnames()
        assert f"{t.id}/all/0/run.out" in names
        assert f"{t.id}/all/1/run.out" in names


class TestSimNetwork:
    def test_ping_pong_end_to_end(self, engine):
        t = run_sim(engine, "network", "ping-pong", instances=2)
        assert t.outcome() == Outcome.SUCCESS
        assert t.result["outcomes"]["all"] == {"total": 2, "ok": 2}
        sim = t.result["journal"]["sim"]
        assert sim["ticks"] > 0 and sim["tick_ms"] == 1.0

    def test_traffic_blocked(self, engine):
        t = run_sim(engine, "network", "traffic-blocked", instances=4)
        assert t.outcome() == Outcome.SUCCESS

    def test_traffic_ruled(self, engine):
        """Per-instance range-rule filters through the full stack: the
        plan asserts exact pre-cut delivery, one-tick rule turnaround,
        and REJECT feedback counts (plans/network TrafficRuled)."""
        t = run_sim(
            engine,
            "network",
            "traffic-ruled",
            instances=6,
            params={"cut_tick": "6", "stop_tick": "20"},
        )
        assert t.outcome() == Outcome.SUCCESS
        m = t.result["journal"]["metrics"]["all"]
        assert m["traffic.received"]["mean"] == 7.0  # cut+1
        assert m["traffic.rejected"]["mean"] == 13.0  # stop-(cut+1)


class TestMemoryPrecheck:
    """Per-run device-memory precheck (VERDICT r4 #8) — the analog of
    the reference's cluster capacity precheck (cluster_k8s.go:958-1012):
    an oversized composition must be refused with a readable error
    BEFORE tracing, not die as an XLA OOM."""

    def test_oversized_composition_refused_cleanly(self, engine):
        t = run_sim(
            engine,
            "placebo",
            "ok",
            instances=64,
            run_params={"memory_limit_bytes": 4096},
        )
        assert t.outcome() == Outcome.FAILURE
        assert "device budget" in (t.error or ""), t.error
        assert "memory_limit_bytes" in (t.error or "")  # override hint

    def test_fitting_composition_passes_and_logs(self, engine):
        t = run_sim(
            engine,
            "placebo",
            "ok",
            instances=8,
            run_params={"memory_limit_bytes": 1 << 30},
        )
        assert t.outcome() == Outcome.SUCCESS
        log = open(engine.task_log_path(t.id)).read()
        assert "memory precheck" in log

    def test_estimate_scales_with_instances(self):
        from testground_tpu.api import RunGroup
        from testground_tpu.sim.engine import SimProgram, build_groups
        from testground_tpu.sim.executor import (
            instantiate_testcase,
            load_sim_testcases,
        )

        def est(n):
            factory = load_sim_testcases(os.path.join(PLANS, "network"))[
                "ping-pong"
            ]
            groups = build_groups(
                [RunGroup(id="all", instances=n, parameters={})]
            )
            tc = instantiate_testcase(factory, groups, 1.0)
            return SimProgram(
                tc, groups, tick_ms=1.0, chunk=8
            ).estimate_carry_bytes()

        small, big = est(64), est(1024)
        assert small > 0
        # calendar/link/state planes are O(N): 16x instances ≈ 16x bytes
        assert 8 * small < big < 32 * small
