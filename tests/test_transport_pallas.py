"""The `transport=pallas` backend vs the XLA scatter path (ISSUE 5).

Three contracts pinned here:

1. **Bit-equality across the dryrun feature matrix**: every workload of
   `__graft_entry__.dryrun_multichip`'s gate — sorted transport,
   filters+regions, direct slots, control lanes, far pairs, duplicate
   shaping, bandwidth queue, filter rules, storm — runs bit-identically
   (status + finished_at + every state leaf + every flow total) under
   `transport="pallas"` and `transport="xla"`. On CPU the kernels run in
   Pallas interpret mode, so tier-1 executes the REAL kernel logic.
2. **Zero-overhead default**: `transport="xla"` (the default) compiles a
   jaxpr-identical program to one built without the knob, with no pallas
   ops and the flat plane layout intact — the pre-PR program, unchanged.
3. **Gating**: the single-device bound (`resolve_transport` falls back
   to xla on a mesh, loudly; `SimProgram` refuses a pallas+mesh build)
   and unknown-value refusal.

Plus chaos equality: a crash/partition/loss schedule with telemetry on
produces the identical per-tick counter stream through both backends.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import __graft_entry__ as ge
from testground_tpu.api import RunGroup
from testground_tpu.sim.api import RUNNING, SUCCESS, Outbox, SimTestcase
from testground_tpu.sim.engine import SimProgram, build_groups
from testground_tpu.sim.executor import resolve_transport
from testground_tpu.sim.faults import build_fault_schedule

# every results() key that is part of the run's observable outcome —
# bit-compared between backends (carry_bytes differs only if the carry
# layout diverged, which the flat/2-D calendar split makes legitimate)
RESULT_KEYS = (
    "status",
    "finished_at",
    "ticks",
    "sync_counts",
    "pub_dropped",
    "latency_clamped",
    "bw_queue_dropped",
    "bw_rate_change_backlogged",
    "collisions",
    "msgs_delivered",
    "msgs_sent",
    "msgs_enqueued",
    "msgs_dropped",
    "msgs_rejected",
    "cal_depth",
    "faults_crashed",
    "faults_restarted",
    "fault_dropped",
)


def assert_runs_equal(label, res_x, res_p):
    for key in RESULT_KEYS:
        a, b = np.asarray(res_x[key]), np.asarray(res_p[key])
        assert np.array_equal(a, b), (
            f"[{label}] xla vs pallas {key} mismatch: {a} vs {b}"
        )
    leaves_x, tree_x = jax.tree.flatten(res_x["states"])
    leaves_p, tree_p = jax.tree.flatten(res_p["states"])
    assert tree_x == tree_p, f"[{label}] state STRUCTURE mismatch"
    for i, (a, b) in enumerate(zip(leaves_x, leaves_p)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            f"[{label}] state leaf {i} mismatch"
        )


def _inline_prog(factory, n, transport, **kw):
    return SimProgram(
        factory(),
        build_groups([RunGroup(id="all", instances=n, parameters={})]),
        test_plan="pallas-ab",
        test_case=factory.__name__,
        tick_ms=1.0,
        chunk=8,
        transport=transport,
        **kw,
    )


# the dryrun_multichip feature matrix, shrunk to single-device CPU test
# scale: (label, make_prog(transport), n, max_ticks). Same plans, same
# parameters, same inline testcases as the gate — only n is smaller.
WORKLOADS = [
    (
        "ping-pong/sorted",
        lambda tr: ge._pingpong_program(8, transport=tr),
        8,
        512,
    ),
    (
        "splitbrain/filters+regions",
        lambda tr: ge._plan_program(
            "splitbrain", "reject", 15, {}, transport=tr
        ),
        15,
        2048,
    ),
    (
        "flood/direct",
        lambda tr: ge._plan_program(
            "benchmarks",
            "pingpong-flood",
            8,
            {"duration_ticks": "64", "latency_ms": "4"},
            transport=tr,
        ),
        8,
        512,
    ),
    (
        "additional-hosts/control-lanes",
        lambda tr: ge._plan_program(
            "additional_hosts",
            "additional_hosts",
            8,
            {},
            hosts=("http-echo",),
            transport=tr,
        ),
        8,
        1024,
    ),
    (
        "far-pairs/pairwise",
        lambda tr: _inline_prog(ge._far_pairs_testcase(), 8, tr),
        8,
        64,
    ),
    (
        "ring/duplicate",
        lambda tr: _inline_prog(ge._dup_ring_testcase(), 8, tr),
        8,
        64,
    ),
    (
        "traffic-shaped/bandwidth-queue",
        lambda tr: ge._plan_program(
            "network",
            "traffic-shaped",
            8,
            {"burst": "12", "rate": "1.5"},
            transport=tr,
        ),
        8,
        256,
    ),
    (
        "ruled-ring/filter-rules",
        lambda tr: _inline_prog(ge._ruled_ring_testcase(), 8, tr),
        8,
        64,
    ),
    (
        "storm/random-graph",
        lambda tr: ge._plan_program(
            "benchmarks",
            "storm",
            16,
            {
                "conn_outgoing": "3",
                "conn_delay_ticks": "8",
                "data_size_kb": "16",
            },
            transport=tr,
        ),
        16,
        512,
    ),
]


class TestDryrunEquality:
    @pytest.mark.parametrize(
        "label,make_prog,n,max_ticks",
        WORKLOADS,
        ids=[w[0] for w in WORKLOADS],
    )
    def test_workload_bit_equal(self, label, make_prog, n, max_ticks):
        res_x = make_prog("xla").run(max_ticks=max_ticks)
        res_p = make_prog("pallas").run(max_ticks=max_ticks)
        # the workload must actually run to SUCCESS — a bit-equal pair
        # of broken runs proves nothing
        ok = int((np.asarray(res_x["status"]) == SUCCESS).sum())
        assert ok == n, (
            f"[{label}] xla arm not all-SUCCESS: {ok}/{n}, "
            f"status={np.asarray(res_x['status']).tolist()}"
        )
        assert res_x["msgs_delivered"] > 0, f"[{label}] no traffic"
        assert_runs_equal(label, res_x, res_p)


class _ChaosBarrierTraffic(SimTestcase):
    """Signal → live-degraded barrier → rotating ring traffic → SUCCESS;
    terminates under any crash subset (sync.live shrinks the barrier)."""

    STATES = ["go"]
    MSG_WIDTH = 1
    OUT_MSGS = 1
    IN_MSGS = 8
    MAX_LINK_TICKS = 8
    SHAPING = ("latency",)
    DURATION = 24

    def init(self, env):
        return {"k": jnp.int32(0), "passed": jnp.asarray(False)}

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        n = env.test_instance_count
        already = sync.last_seq[self.state_id("go")] > 0
        counts = sync.counts[self.state_id("go")]
        passed = state["passed"] | (
            (counts > 0) & (counts >= jnp.sum(sync.live))
        )
        k = jnp.where(passed, state["k"] + 1, state["k"])
        return self.out(
            {"k": k, "passed": passed},
            status=jnp.where(k >= cls.DURATION, SUCCESS, RUNNING),
            outbox=Outbox.single(
                jnp.mod(env.global_seq + 1 + t, n),
                jnp.zeros((1,), jnp.int32),
                passed,
                cls.OUT_MSGS,
                cls.MSG_WIDTH,
            ),
            signals=self.signal("go") * ~already,
        )


class TestChaosEquality:
    def test_chaos_schedule_streams_bit_equal(self):
        """Crash + restart + partition + loss through BOTH backends: the
        full results surface AND the per-tick telemetry counter stream
        must match bit for bit (fault kills happen inside enqueue, where
        the pallas commit kernel replaces the scatters)."""
        n = 6
        events = [
            {"kind": "crash", "instances": "2:4", "start_ms": 4.0},
            {"kind": "restart", "instances": "2:3", "start_ms": 9.0},
            {
                "kind": "partition",
                "instances": "0:2",
                "to_instances": "4:6",
                "start_ms": 3.0,
                "duration_ms": 6.0,
                "bidirectional": True,
            },
            {
                "kind": "loss_burst",
                "instances": "0:6",
                "start_ms": 6.0,
                "duration_ms": 8.0,
                "loss": 50.0,
            },
        ]
        groups = build_groups(
            [RunGroup(id="all", instances=n, parameters={})]
        )
        faults = build_fault_schedule(groups, {"all": events}, 1.0)

        def run(transport):
            prog = SimProgram(
                _ChaosBarrierTraffic(),
                groups,
                test_plan="pallas-ab",
                test_case="chaos",
                tick_ms=1.0,
                chunk=16,
                telemetry=True,
                faults=faults,
                transport=transport,
            )
            blocks = []
            res = prog.run(
                seed=7,
                max_ticks=2048,
                telemetry_cb=lambda b: blocks.append(np.asarray(b).copy()),
            )
            return res, np.concatenate(blocks)

        res_x, stream_x = run("xla")
        res_p, stream_p = run("pallas")
        assert res_x["faults_crashed"] > 0  # the schedule actually fired
        assert res_x["msgs_delivered"] > 0
        assert_runs_equal("chaos", res_x, res_p)
        assert np.array_equal(stream_x, stream_p), (
            "telemetry counter streams diverge between backends"
        )


class TestZeroOverheadDefault:
    def test_default_xla_program_is_jaxpr_identical_and_pallas_free(self):
        """The zero-overhead contract: a program built WITHOUT the knob
        traces the identical chunk jaxpr as transport='xla', contains no
        pallas call, and keeps the flat plane layout — the exact pre-PR
        program. The pallas build of the same workload differs and DOES
        carry the kernels."""
        make = lambda **kw: ge._pingpong_program(8, **kw)
        base = make()
        explicit = make(transport="xla")
        carry = jax.jit(lambda: base.init_carry(0))()
        j_base = str(jax.make_jaxpr(base._chunk_step)(carry))
        assert str(jax.make_jaxpr(explicit._chunk_step)(carry)) == j_base
        assert "pallas" not in j_base
        assert base.transport == "xla"
        # unsharded xla keeps the flat [L·N·SLOTS] planes (PERF.md layout)
        assert carry.cal.flat

        pal = make(transport="pallas")
        carry_p = jax.jit(lambda: pal.init_carry(0))()
        j_pal = str(jax.make_jaxpr(pal._chunk_step)(carry_p))
        assert "pallas" in j_pal
        assert not carry_p.cal.flat


class TestTransportGating:
    def test_unknown_transport_refused(self):
        with pytest.raises(ValueError, match="unknown transport"):
            ge._pingpong_program(8, transport="cuda")

    def test_pallas_on_mesh_refused_by_program(self):
        devs = jax.devices()[:2]
        mesh = jax.sharding.Mesh(np.asarray(devs), ("i",))
        with pytest.raises(ValueError, match="single-device"):
            ge._pingpong_program(8, mesh=mesh, transport="pallas")

    def test_resolve_transport_gate(self):
        cfg = dataclasses.make_dataclass("Cfg", [("transport", str)])

        assert resolve_transport(cfg("xla"), None) == "xla"
        assert resolve_transport(cfg("pallas"), None) == "pallas"
        assert resolve_transport(cfg("PALLAS"), None) == "pallas"
        assert resolve_transport(cfg(""), None) == "xla"
        with pytest.raises(ValueError, match="unknown transport"):
            resolve_transport(cfg("tpu"), None)

        # a mesh forces xla, loudly — the single-device bound
        devs = jax.devices()[:2]
        mesh = jax.sharding.Mesh(np.asarray(devs), ("i",))
        warned = []
        assert (
            resolve_transport(
                cfg("pallas"), mesh, lambda fmt, *a: warned.append(fmt % a)
            )
            == "xla"
        )
        assert warned and "single device" in warned[0]
        # xla on a mesh stays silent
        assert resolve_transport(cfg("xla"), mesh) == "xla"
