"""The `transport=pallas` backend vs the XLA scatter path (ISSUE 5).

Three contracts pinned here:

1. **Bit-equality across the dryrun feature matrix**: every workload of
   `__graft_entry__.dryrun_multichip`'s gate — sorted transport,
   filters+regions, direct slots, control lanes, far pairs, duplicate
   shaping, bandwidth queue, filter rules, storm — runs bit-identically
   (status + finished_at + every state leaf + every flow total) under
   `transport="pallas"` and `transport="xla"`. On CPU the kernels run in
   Pallas interpret mode, so tier-1 executes the REAL kernel logic.
2. **Zero-overhead default**: `transport="xla"` (the default) compiles a
   jaxpr-identical program to one built without the knob, with no pallas
   ops and the flat plane layout intact — the pre-PR program, unchanged.
3. **Gating**: the mesh divisibility bound (`decide_transport` resolves
   indivisible lane counts to xla, loudly; `SimProgram`'s own backstop
   refuses an indivisible pallas+mesh build) and unknown-value refusal.

Plus chaos equality: a crash/partition/loss schedule with telemetry on
produces the identical per-tick counter stream through both backends.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import __graft_entry__ as ge
from testground_tpu.api import RunGroup
from testground_tpu.sim.api import RUNNING, SUCCESS, Outbox, SimTestcase
from testground_tpu.sim.engine import SimProgram, build_groups
from testground_tpu.sim.executor import resolve_transport
from testground_tpu.sim.faults import build_fault_schedule

# every results() key that is part of the run's observable outcome —
# bit-compared between backends (carry_bytes differs only if the carry
# layout diverged, which the flat/2-D calendar split makes legitimate)
RESULT_KEYS = (
    "status",
    "finished_at",
    "ticks",
    "sync_counts",
    "pub_dropped",
    "latency_clamped",
    "bw_queue_dropped",
    "bw_rate_change_backlogged",
    "collisions",
    "msgs_delivered",
    "msgs_sent",
    "msgs_enqueued",
    "msgs_dropped",
    "msgs_rejected",
    "cal_depth",
    "faults_crashed",
    "faults_restarted",
    "fault_dropped",
)


def assert_runs_equal(label, res_x, res_p):
    for key in RESULT_KEYS:
        a, b = np.asarray(res_x[key]), np.asarray(res_p[key])
        assert np.array_equal(a, b), (
            f"[{label}] xla vs pallas {key} mismatch: {a} vs {b}"
        )
    leaves_x, tree_x = jax.tree.flatten(res_x["states"])
    leaves_p, tree_p = jax.tree.flatten(res_p["states"])
    assert tree_x == tree_p, f"[{label}] state STRUCTURE mismatch"
    for i, (a, b) in enumerate(zip(leaves_x, leaves_p)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            f"[{label}] state leaf {i} mismatch"
        )


def _inline_prog(factory, n, transport, **kw):
    return SimProgram(
        factory(),
        build_groups([RunGroup(id="all", instances=n, parameters={})]),
        test_plan="pallas-ab",
        test_case=factory.__name__,
        tick_ms=1.0,
        chunk=8,
        transport=transport,
        **kw,
    )


# the dryrun_multichip feature matrix, shrunk to single-device CPU test
# scale: (label, make_prog(transport), n, max_ticks). Same plans, same
# parameters, same inline testcases as the gate — only n is smaller.
WORKLOADS = [
    (
        "ping-pong/sorted",
        lambda tr: ge._pingpong_program(8, transport=tr),
        8,
        512,
    ),
    (
        "splitbrain/filters+regions",
        lambda tr: ge._plan_program(
            "splitbrain", "reject", 15, {}, transport=tr
        ),
        15,
        2048,
    ),
    (
        "flood/direct",
        lambda tr: ge._plan_program(
            "benchmarks",
            "pingpong-flood",
            8,
            {"duration_ticks": "64", "latency_ms": "4"},
            transport=tr,
        ),
        8,
        512,
    ),
    (
        "additional-hosts/control-lanes",
        lambda tr: ge._plan_program(
            "additional_hosts",
            "additional_hosts",
            8,
            {},
            hosts=("http-echo",),
            transport=tr,
        ),
        8,
        1024,
    ),
    (
        "far-pairs/pairwise",
        lambda tr: _inline_prog(ge._far_pairs_testcase(), 8, tr),
        8,
        64,
    ),
    (
        "ring/duplicate",
        lambda tr: _inline_prog(ge._dup_ring_testcase(), 8, tr),
        8,
        64,
    ),
    (
        "traffic-shaped/bandwidth-queue",
        lambda tr: ge._plan_program(
            "network",
            "traffic-shaped",
            8,
            {"burst": "12", "rate": "1.5"},
            transport=tr,
        ),
        8,
        256,
    ),
    (
        "ruled-ring/filter-rules",
        lambda tr: _inline_prog(ge._ruled_ring_testcase(), 8, tr),
        8,
        64,
    ),
    (
        "storm/random-graph",
        lambda tr: ge._plan_program(
            "benchmarks",
            "storm",
            16,
            {
                "conn_outgoing": "3",
                "conn_delay_ticks": "8",
                "data_size_kb": "16",
            },
            transport=tr,
        ),
        16,
        512,
    ),
]


class TestDryrunEquality:
    @pytest.mark.parametrize(
        "label,make_prog,n,max_ticks",
        WORKLOADS,
        ids=[w[0] for w in WORKLOADS],
    )
    def test_workload_bit_equal(self, label, make_prog, n, max_ticks):
        res_x = make_prog("xla").run(max_ticks=max_ticks)
        res_p = make_prog("pallas").run(max_ticks=max_ticks)
        # the workload must actually run to SUCCESS — a bit-equal pair
        # of broken runs proves nothing
        ok = int((np.asarray(res_x["status"]) == SUCCESS).sum())
        assert ok == n, (
            f"[{label}] xla arm not all-SUCCESS: {ok}/{n}, "
            f"status={np.asarray(res_x['status']).tolist()}"
        )
        assert res_x["msgs_delivered"] > 0, f"[{label}] no traffic"
        assert_runs_equal(label, res_x, res_p)


class _ChaosBarrierTraffic(SimTestcase):
    """Signal → live-degraded barrier → rotating ring traffic → SUCCESS;
    terminates under any crash subset (sync.live shrinks the barrier)."""

    STATES = ["go"]
    MSG_WIDTH = 1
    OUT_MSGS = 1
    IN_MSGS = 8
    MAX_LINK_TICKS = 8
    SHAPING = ("latency",)
    DURATION = 24

    def init(self, env):
        return {"k": jnp.int32(0), "passed": jnp.asarray(False)}

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        n = env.test_instance_count
        already = sync.last_seq[self.state_id("go")] > 0
        counts = sync.counts[self.state_id("go")]
        passed = state["passed"] | (
            (counts > 0) & (counts >= jnp.sum(sync.live))
        )
        k = jnp.where(passed, state["k"] + 1, state["k"])
        return self.out(
            {"k": k, "passed": passed},
            status=jnp.where(k >= cls.DURATION, SUCCESS, RUNNING),
            outbox=Outbox.single(
                jnp.mod(env.global_seq + 1 + t, n),
                jnp.zeros((1,), jnp.int32),
                passed,
                cls.OUT_MSGS,
                cls.MSG_WIDTH,
            ),
            signals=self.signal("go") * ~already,
        )


class TestChaosEquality:
    def test_chaos_schedule_streams_bit_equal(self):
        """Crash + restart + partition + loss through BOTH backends: the
        full results surface AND the per-tick telemetry counter stream
        must match bit for bit (fault kills happen inside enqueue, where
        the pallas commit kernel replaces the scatters)."""
        n = 6
        events = [
            {"kind": "crash", "instances": "2:4", "start_ms": 4.0},
            {"kind": "restart", "instances": "2:3", "start_ms": 9.0},
            {
                "kind": "partition",
                "instances": "0:2",
                "to_instances": "4:6",
                "start_ms": 3.0,
                "duration_ms": 6.0,
                "bidirectional": True,
            },
            {
                "kind": "loss_burst",
                "instances": "0:6",
                "start_ms": 6.0,
                "duration_ms": 8.0,
                "loss": 50.0,
            },
        ]
        groups = build_groups(
            [RunGroup(id="all", instances=n, parameters={})]
        )
        faults = build_fault_schedule(groups, {"all": events}, 1.0)

        def run(transport):
            prog = SimProgram(
                _ChaosBarrierTraffic(),
                groups,
                test_plan="pallas-ab",
                test_case="chaos",
                tick_ms=1.0,
                chunk=16,
                telemetry=True,
                faults=faults,
                transport=transport,
            )
            blocks = []
            res = prog.run(
                seed=7,
                max_ticks=2048,
                telemetry_cb=lambda b: blocks.append(np.asarray(b).copy()),
            )
            return res, np.concatenate(blocks)

        res_x, stream_x = run("xla")
        res_p, stream_p = run("pallas")
        assert res_x["faults_crashed"] > 0  # the schedule actually fired
        assert res_x["msgs_delivered"] > 0
        assert_runs_equal("chaos", res_x, res_p)
        assert np.array_equal(stream_x, stream_p), (
            "telemetry counter streams diverge between backends"
        )


class TestZeroOverheadDefault:
    def test_default_xla_program_is_jaxpr_identical_and_pallas_free(self):
        """The zero-overhead contract: a program built WITHOUT the knob
        traces the identical chunk jaxpr as transport='xla', contains no
        pallas call, and keeps the flat plane layout — the exact pre-PR
        program. The pallas build of the same workload differs and DOES
        carry the kernels."""
        make = lambda **kw: ge._pingpong_program(8, **kw)
        base = make()
        explicit = make(transport="xla")
        carry = jax.jit(lambda: base.init_carry(0))()
        j_base = str(jax.make_jaxpr(base._chunk_step)(carry))
        assert str(jax.make_jaxpr(explicit._chunk_step)(carry)) == j_base
        assert "pallas" not in j_base
        assert base.transport == "xla"
        # unsharded xla keeps the flat [L·N·SLOTS] planes (PERF.md layout)
        assert carry.cal.flat

        pal = make(transport="pallas")
        carry_p = jax.jit(lambda: pal.init_carry(0))()
        j_pal = str(jax.make_jaxpr(pal._chunk_step)(carry_p))
        assert "pallas" in j_pal
        assert not carry_p.cal.flat


def _np_commit(occ0, pay0, sk, occ_vals, pay, n, slots, horizon, stacking):
    """Plain-python reference of the sorted-stream commit semantics:
    rank within each (bucket, dst) run + the bucket's PRE-tick fill,
    survival = rank < slots — the contract both the XLA scatter path
    and the segmented kernel implement."""
    occ = occ0.copy()
    payp = pay0.copy()
    surv = np.zeros(len(sk), np.int32)
    prev = None
    nxt = 0
    for j, key in enumerate(int(k) for k in sk):
        if key >= horizon * n:
            continue
        b, d = divmod(key, n)
        if key != prev:
            slot = (
                sum(int(occ0[b, s * n + d] != 0) for s in range(slots))
                if stacking
                else 0
            )
            prev = key
        else:
            slot = nxt
        if slot < slots:
            pos = slot * n + d
            occ[b, pos] = occ_vals[j]
            payp[b, pos] = pay[j]
            surv[j] = 1
        nxt = slot + 1
    return occ, payp, surv


class TestSegmentedTileCarry:
    """Tile-boundary edge cases of the segmented commit kernel (ISSUE
    14): the SMEM rank carry across stream tiles, runs starting exactly
    at a tile edge, and the stacking base read when a bucket's segment
    spans tiles — each pinned against the python reference with a tile
    small enough that the crafted streams genuinely cross boundaries."""

    N, SLOTS, HORIZON, TILE = 128, 4, 4, 128

    def _commit(self, sk_np, occ0=None, stacking=True, tile=None):
        from testground_tpu.sim.net import Calendar
        from testground_tpu.sim.pallas_transport import commit_calendar

        n, slots, horizon = self.N, self.SLOTS, self.HORIZON
        cal = Calendar.empty(horizon, n, slots, width=1, track_src=True)
        if occ0 is not None:
            cal = dataclasses.replace(cal, src=jnp.asarray(occ0, jnp.int32))
        m2 = len(sk_np)
        sk = jnp.asarray(sk_np, jnp.int32)
        occ_vals = jnp.arange(2, m2 + 2, dtype=jnp.int32)  # distinct marks
        pay = [jnp.arange(1000, 1000 + m2, dtype=jnp.int32)]
        cal2, surv = commit_calendar(
            cal,
            sk,
            occ_vals,
            pay,
            jnp.int32(0),
            stacking=stacking,
            tile=self.TILE if tile is None else tile,
        )
        occ0_np = (
            np.zeros((horizon, n * slots), np.int32)
            if occ0 is None
            else np.asarray(occ0, np.int32)
        )
        ref_occ, ref_pay, ref_surv = _np_commit(
            occ0_np,
            np.zeros((horizon, n * slots), np.int32),
            sk_np,
            np.arange(2, m2 + 2, dtype=np.int32),
            np.arange(1000, 1000 + m2, dtype=np.int32),
            n,
            slots,
            horizon,
            stacking,
        )
        np.testing.assert_array_equal(np.asarray(cal2.src), ref_occ)
        np.testing.assert_array_equal(np.asarray(cal2.payload[0]), ref_pay)
        np.testing.assert_array_equal(np.asarray(surv), ref_surv)
        return np.asarray(surv)

    def test_run_spanning_two_tiles_keeps_rank(self):
        """A 5-message (bucket, dst) run crossing the tile boundary at
        position 128: slots 0-3 survive (two before the cut, two
        after), the 5th overflows — the rank must NOT restart at the
        tile edge."""
        sk = list(range(126)) + [200] * 5 + [512] * 125
        surv = self._commit(sk)
        assert surv[122:126].tolist() == [1, 1, 1, 1]  # singleton runs
        assert surv[126:131].tolist() == [1, 1, 1, 1, 0]

    def test_run_starting_at_tile_edge(self):
        """A run whose FIRST message sits exactly at a tile start: the
        fresh-run fill read happens in the new tile with the carry
        handed over from the previous one."""
        sk = list(range(128)) + [300, 300] + [512] * 126
        surv = self._commit(sk)
        assert surv[:130].tolist() == [1] * 130

    def test_stacking_base_spans_tiles(self):
        """Pre-tick occupancy shifts the rank base of a tile-spanning
        run: 2 slots of (bucket 1, dst 72) already taken → the 3-message
        run gets slots 2, 3 and one overflow, split across the tile
        cut."""
        n, slots, horizon = self.N, self.SLOTS, self.HORIZON
        occ0 = np.zeros((horizon, n * slots), np.int32)
        occ0[1, 0 * n + 72] = 7  # slot 0 of dst 72 in bucket 1
        occ0[1, 1 * n + 72] = 9  # slot 1
        key = 1 * n + 72  # = 200, sorted after the 0..126 prefix
        # positions 127, 128, 129 hold the run — the tile cut falls
        # between its first and second message, so the base read
        # happens in tile 0 and the carry crosses into tile 1
        sk = list(range(0, 127)) + [key] * 3 + [512] * 126
        surv = self._commit(sk, occ0=occ0)
        assert surv[127:130].tolist() == [1, 1, 0]

    def test_without_stacking_rank_restarts_at_zero(self):
        n = self.N
        occ0 = np.zeros((self.HORIZON, n * self.SLOTS), np.int32)
        occ0[1, 0 * n + 5] = 3
        key = 1 * n + 5
        sk = list(range(0, 127)) + [key] * 2 + [512] * 127
        surv = self._commit(sk, occ0=occ0, stacking=False)
        assert surv[127:129].tolist() == [1, 1]

    def test_tile_size_invariance_on_random_stream(self):
        """A random sorted stream commits identically at tile 128, tile
        512, and one whole-stream tile — the segmentation is invisible
        to the results by construction."""
        from testground_tpu.sim.net import Calendar
        from testground_tpu.sim.pallas_transport import commit_calendar

        n, slots, horizon = self.N, self.SLOTS, self.HORIZON
        rng = np.random.default_rng(7)
        m2 = 700  # not a tile multiple: exercises the padded tail
        keys = np.sort(
            rng.integers(0, horizon * n + 40, size=m2)
        )  # some invalid
        keys = np.minimum(keys, horizon * n).astype(np.int32)
        outs = []
        for tile in (128, 512, 1024):
            cal = Calendar.empty(horizon, n, slots, width=1, track_src=True)
            cal2, surv = commit_calendar(
                cal,
                jnp.asarray(keys),
                jnp.arange(2, m2 + 2, dtype=jnp.int32),
                [jnp.arange(m2, dtype=jnp.int32)],
                jnp.int32(3),
                stacking=True,
                tile=tile,
            )
            outs.append(
                (np.asarray(cal2.src), np.asarray(cal2.payload[0]),
                 np.asarray(surv))
            )
        for got in outs[1:]:
            for a, b in zip(outs[0], got):
                np.testing.assert_array_equal(a, b)


class TestCommitCallCache:
    def test_cache_key_is_reduced_config_with_headroom(self):
        """The lru_cache bugfix (ISSUE 14): the key is the REDUCED
        static config — track_src is gone from it (the kernel never
        read it; it co-varies with the occupancy dtype that IS keyed),
        eager same-shape calls hit, and the bound has headroom for the
        segmented (m2p, tile) combinations the fuzz suites multiply."""
        import inspect

        from testground_tpu.sim.net import Calendar
        from testground_tpu.sim.pallas_transport import (
            _commit_call,
            commit_calendar,
        )

        assert "track_src" not in inspect.signature(
            _commit_call.__wrapped__
        ).parameters
        _commit_call.cache_clear()
        n, slots, horizon, m2 = 64, 2, 4, 256
        sk = jnp.full((m2,), horizon * n, jnp.int32)  # all invalid
        occ_vals = jnp.ones((m2,), jnp.int32)
        pay = [jnp.zeros((m2,), jnp.int32)]
        cal = Calendar.empty(horizon, n, slots, width=1, track_src=True)
        for _ in range(3):  # the fuzz suites hit this eagerly per tick
            commit_calendar(cal, sk, occ_vals, pay, jnp.int32(0))
        info = _commit_call.cache_info()
        assert info.misses == 1 and info.hits == 2, info
        assert info.maxsize >= 256  # the segmented configs need headroom

    def test_cache_key_pads_stream_length_to_tile_grain(self):
        """Nearby fuzz shapes share an entry: m2 enters the key padded
        up to the tile grain, so 600- and 700-long streams at tile 1024
        compile once."""
        from testground_tpu.sim.net import Calendar
        from testground_tpu.sim.pallas_transport import (
            _commit_call,
            commit_calendar,
        )

        _commit_call.cache_clear()
        n, slots, horizon = 64, 2, 4
        cal = Calendar.empty(horizon, n, slots, width=1, track_src=True)
        for m2 in (600, 700):
            commit_calendar(
                cal,
                jnp.full((m2,), horizon * n, jnp.int32),
                jnp.ones((m2,), jnp.int32),
                [jnp.zeros((m2,), jnp.int32)],
                jnp.int32(0),
                tile=1024,
            )
        info = _commit_call.cache_info()
        assert info.misses == 1 and info.hits == 1, info


@pytest.mark.slow
class TestSegmentedEnvelope:
    """The ISSUE-14 acceptance pins: compositions whose sorted-stream
    footprint exceeds the ISSUE-5 kernel's ~16 MB whole-stream VMEM
    envelope run under ``transport=pallas`` — no fallback, no cap
    error — bit-equal to the XLA path in interpret mode. Interpret
    mode executes the real segmented kernel logic over hundreds of
    stream tiles, so the tile enumeration, rank carry, and survival
    bookkeeping are all exercised at scale."""

    def test_flagship_past_500k_instances_bit_equal(self):
        """pingpong-sustained at 540k instances: m2 = 2N ≈ 1.08M
        messages/tick, sorted-stream footprint (3+W)·m2·4B ≈ 17.3 MB —
        past the old whole-stream envelope. Status + every state leaf +
        every flow total identical across backends."""
        n = 540_672
        params = {
            "duration_ticks": "64",
            "latency_ms": "4",
            "latency2_ms": "2",
            "reshape_every": "1000",
        }

        def run(tr):
            return ge._plan_program(
                "network",
                "pingpong-sustained",
                n,
                params,
                chunk=4,
                transport=tr,
            ).run(max_ticks=8)

        res_x = run("xla")
        res_p = run("pallas")
        assert res_x["msgs_delivered"] > 0
        assert_runs_equal("flagship@540k", res_x, res_p)

    def test_storm_at_100k_bit_equal(self):
        """storm at 100k instances (the shape PERF.md excluded 'well
        below 100k'): Poisson fan-in over a random graph through the
        sorted path, multi-message (bucket, dst) runs everywhere —
        the adversarial shape for the tile-boundary rank carry."""
        params = {
            "conn_outgoing": "3",
            "conn_delay_ticks": "8",
            "data_size_kb": "4096",
        }

        def run(tr):
            return ge._plan_program(
                "benchmarks", "storm", 100_000, params, chunk=4,
                transport=tr,
            ).run(max_ticks=16)

        res_x = run("xla")
        res_p = run("pallas")
        assert res_x["msgs_delivered"] > 0
        assert_runs_equal("storm@100k", res_x, res_p)

    def test_fate_plane_over_envelope(self):
        """The flight recorder's per-message fate plane at an
        over-envelope stream (m = 544·2048 ≈ 1.11M messages in one
        tick): ``enqueue(want_fate=True)`` through both backends
        returns the identical fate code per original message, plus
        identical planes and flow counters."""
        from testground_tpu.sim import net
        from testground_tpu.sim.net import Calendar, enqueue

        n, o, slots, horizon = 2048, 544, 4, 8
        cal_shape = dict(track_src=True, flat=False)
        link = net.make_link_state(n, 1, [4.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0])
        rng = np.random.default_rng(3)
        dst = jnp.asarray(
            rng.integers(0, n, size=(o, n)), jnp.int32
        )
        payload = jnp.asarray(
            rng.integers(0, 1 << 20, size=(o, 1, n)), jnp.int32
        )
        valid = jnp.asarray(rng.random((o, n)) < 0.9)

        def run(tr):
            cal = Calendar.empty(horizon, n, slots, 1, **cal_shape)
            cal2, fb = enqueue(
                cal,
                link,
                dst,
                payload,
                valid,
                jnp.int32(0),
                1.0,
                jax.random.key(11),
                features=("latency",),
                want_fate=True,
                transport=tr,
            )
            return cal2, fb

        cal_x, fb_x = run("xla")
        cal_p, fb_p = run("pallas")
        assert np.asarray(fb_x.fate).shape == (o * n,)
        np.testing.assert_array_equal(
            np.asarray(fb_x.fate), np.asarray(fb_p.fate)
        )
        for name in ("sent", "enqueued", "rejected", "clamped"):
            assert np.array_equal(
                np.asarray(getattr(fb_x, name)),
                np.asarray(getattr(fb_p, name)),
            ), name
        np.testing.assert_array_equal(
            np.asarray(cal_x.src), np.asarray(cal_p.src)
        )
        np.testing.assert_array_equal(
            np.asarray(cal_x.payload[0]), np.asarray(cal_p.payload[0])
        )
        # the shape genuinely exceeds the old whole-stream envelope
        assert (3 + 1) * o * n * 4 > 16 * 2**20


class TestTransportGating:
    def test_unknown_transport_refused(self):
        with pytest.raises(ValueError, match="unknown transport"):
            ge._pingpong_program(8, transport="cuda")

    def test_pallas_on_indivisible_mesh_refused_by_program(self):
        # 8 lanes do not divide across 3 peer shards — the engine's own
        # divisibility backstop refuses; a divisible mesh builds fine
        devs = jax.devices()[:3]
        mesh = jax.sharding.Mesh(np.asarray(devs), ("i",))
        with pytest.raises(ValueError, match="divide across the peer"):
            ge._pingpong_program(8, mesh=mesh, transport="pallas")
        devs = jax.devices()[:2]
        mesh = jax.sharding.Mesh(np.asarray(devs), ("i",))
        prog = ge._pingpong_program(8, mesh=mesh, transport="pallas")
        assert prog.transport == "pallas"

    def test_resolve_transport_gate(self):
        cfg = dataclasses.make_dataclass("Cfg", [("transport", str)])

        assert resolve_transport(cfg("xla"), None) == "xla"
        assert resolve_transport(cfg("pallas"), None) == "pallas"
        assert resolve_transport(cfg("PALLAS"), None) == "pallas"
        assert resolve_transport(cfg(""), None) == "xla"
        with pytest.raises(ValueError, match="unknown transport"):
            resolve_transport(cfg("tpu"), None)

        # explicit pallas on a mesh passes through contextless — the
        # divisibility check needs lane counts, so without a context the
        # gate defers to the engine's own backstop instead of guessing
        devs = jax.devices()[:2]
        mesh = jax.sharding.Mesh(np.asarray(devs), ("i",))
        warned = []
        assert (
            resolve_transport(
                cfg("pallas"), mesh, lambda fmt, *a: warned.append(fmt % a)
            )
            == "pallas"
        )
        assert not warned
        # xla on a mesh stays silent
        assert resolve_transport(cfg("xla"), mesh) == "xla"
