"""Composition templating tests (reference: ``pkg/cmd/template_test.go`` +
``pkg/cmd/fixtures/templates/``). Fixtures here are written fresh against the
same construct set: with/range/define+template, pick|toml, withEnv, atoi,
index, split, load_resource, trim markers."""

import os
import time
from testground_tpu.utils.compat import tomllib

import pytest

from testground_tpu.api import (
    TemplateError,
    TestPlanManifest,
    load_composition,
    prepare_for_run,
    render_template,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


def test_plain_text_passthrough():
    src = '[global]\nplan = "x"\n'
    assert render_template(src, env={}) == src


def test_env_interpolation_both_spellings():
    out = render_template(
        'a = "{{ .Env.FOO }}"\nb = "{{ $.Env.FOO }}"\n', env={"FOO": "42"}
    )
    assert out == 'a = "42"\nb = "42"\n'


def test_with_load_resource_and_trim(tmp_path):
    (tmp_path / "res.toml").write_text('go_version = "1.21"\nselector = "fast"\n')
    src = (
        "[global]\n"
        '{{ with (load_resource "./res.toml") -}}\n'
        "version = \"{{ .go_version }}\"\n"
        "selector = \"{{ .selector }}\"\n"
        "{{- end }}\n"
    )
    out = render_template(src, env={}, template_dir=str(tmp_path))
    doc = tomllib.loads(out)
    assert doc["global"] == {"version": "1.21", "selector": "fast"}


def test_range_over_resource_groups(tmp_path):
    (tmp_path / "groups.toml").write_text(
        "[[groups]]\nid = \"a\"\nn = 1\n[[groups]]\nid = \"b\"\nn = 2\n"
    )
    src = (
        '{{ with (load_resource "./groups.toml") }}'
        "{{- range .groups }}\n"
        "[[groups]]\n"
        'id = "{{ .id }}"\n'
        "count = {{ .n }}\n"
        "{{- end }}\n"
        "{{- end }}"
    )
    doc = tomllib.loads(render_template(src, env={}, template_dir=str(tmp_path)))
    assert [g["id"] for g in doc["groups"]] == ["a", "b"]
    assert [g["count"] for g in doc["groups"]] == [1, 2]


def test_define_template_with_env(tmp_path):
    (tmp_path / "res.toml").write_text('go_version = "1.21"\n')
    src = (
        '{{ define "partial" -}}\n'
        "[meta]\n"
        'from_env = "{{ $.Env.MyValue }}"\n'
        'version = "{{ .go_version }}"\n'
        "{{- end -}}\n"
        '{{ with (load_resource "./res.toml") }}'
        '{{ template "partial" (withEnv .) }}'
        "{{ end }}"
    )
    doc = tomllib.loads(
        render_template(src, env={"MyValue": "123"}, template_dir=str(tmp_path))
    )
    assert doc["meta"] == {"from_env": "123", "version": "1.21"}


def test_pick_pipe_toml(tmp_path):
    (tmp_path / "res.toml").write_text(
        'other = "ignored"\n[[values]]\nid = "v0"\n[[values]]\nid = "v1"\n'
    )
    src = (
        '{{ with (load_resource "./res.toml") }}'
        'second = "{{ (index .values (atoi "1")).id }}"\n'
        "{{ (pick . \"values\") | toml }}"
        "{{ end }}"
    )
    doc = tomllib.loads(render_template(src, env={}, template_dir=str(tmp_path)))
    assert [v["id"] for v in doc["values"]] == ["v0", "v1"]
    assert doc["second"] == "v1"


def test_split_and_range():
    src = (
        "{{ range (split .Env.REGIONS) }}"
        "[[groups]]\n"
        'id = "{{ . }}"\n'
        "{{ end }}"
    )
    doc = tomllib.loads(render_template(src, env={"REGIONS": "eu,us,ap"}))
    assert [g["id"] for g in doc["groups"]] == ["eu", "us", "ap"]


def test_if_else():
    src = '{{ if .Env.BIG }}n = 100{{ else }}n = 1{{ end }}\n'
    assert tomllib.loads(render_template(src, env={"BIG": "y"}))["n"] == 100
    assert tomllib.loads(render_template(src, env={}))["n"] == 1


def test_else_if_chain():
    src = (
        "{{ if .Env.A }}x = 1{{ else if .Env.B }}x = 2"
        "{{ else }}x = 3{{ end }}\n"
    )
    assert tomllib.loads(render_template(src, env={"A": "y"}))["x"] == 1
    assert tomllib.loads(render_template(src, env={"B": "y"}))["x"] == 2
    assert tomllib.loads(render_template(src, env={}))["x"] == 3


def test_comment_consumed():
    out = render_template("a = 1\n{{/* note */}}\nb = 2\n", env={})
    assert tomllib.loads(out) == {"a": 1, "b": 2}
    assert render_template("x{{- /* note */ -}}y", env={}) == "xy"


def test_missing_resource_raises(tmp_path):
    src = '{{ with (load_resource "./nope.toml") }}{{ end }}'
    with pytest.raises(TemplateError):
        render_template(src, env={}, template_dir=str(tmp_path))


def test_unknown_function_raises():
    with pytest.raises(TemplateError):
        render_template("{{ frobnicate 1 }}", env={})


def test_unterminated_block_raises():
    with pytest.raises(TemplateError):
        render_template("{{ with .Env }}no end", env={})


def test_atoi_bad_input_raises():
    with pytest.raises(TemplateError):
        render_template('{{ atoi "xyz" }}', env={})


def test_templated_composition_loads_and_prepares(tmp_path, monkeypatch):
    """End-to-end: a templated composition renders through load_composition
    and survives full run preparation against the real placebo manifest."""
    monkeypatch.setenv("TG_TPU_COUNT", "3")
    comp_path = tmp_path / "comp.toml"
    comp_path.write_text(
        "[global]\n"
        'plan = "placebo"\ncase = "ok"\nbuilder = "sim:plan"\nrunner = "sim:jax"\n'
        "total_instances = {{ atoi .Env.TG_TPU_COUNT }}\n"
        "[[groups]]\n"
        'id = "all"\n'
        "[groups.instances]\ncount = {{ atoi .Env.TG_TPU_COUNT }}\n"
    )
    comp = load_composition(comp_path)
    assert comp.global_.total_instances == 3
    assert comp.runs, "default run synthesized"
    manifest = TestPlanManifest.load_file(
        os.path.join(PLANS, "placebo", "manifest.toml")
    )
    prepared = prepare_for_run(comp, manifest)
    assert prepared.runs[0].total_instances == 3


def test_templated_composition_runs_end_to_end(tmp_path, tg_home, monkeypatch):
    """Render → queue → execute on the in-process engine (local:exec)."""
    from testground_tpu.builders.exec_py import ExecPyBuilder
    from testground_tpu.config import EnvConfig
    from testground_tpu.engine import Engine, EngineConfig, Outcome, State
    from testground_tpu.runners.local_exec import LocalExecRunner

    monkeypatch.setenv("TG_TPU_COUNT", "2")
    comp_path = tmp_path / "comp.toml"
    comp_path.write_text(
        "[global]\n"
        'plan = "placebo"\ncase = "ok"\nbuilder = "exec:py"\nrunner = "local:exec"\n'
        "[[groups]]\n"
        'id = "all"\n'
        "[groups.instances]\ncount = {{ atoi .Env.TG_TPU_COUNT }}\n"
    )
    comp = load_composition(comp_path)
    manifest = TestPlanManifest.load_file(
        os.path.join(PLANS, "placebo", "manifest.toml")
    )
    engine = Engine(
        EngineConfig(
            env=EnvConfig.load(),
            builders=[ExecPyBuilder()],
            runners=[LocalExecRunner()],
        )
    )
    engine.start_workers()
    try:
        tid = engine.queue_run(
            comp, manifest, sources_dir=os.path.join(PLANS, "placebo")
        )
        deadline = time.time() + 60
        task = None
        while time.time() < deadline:
            task = engine.get_task(tid)
            if task is not None and task.state().state in (
                State.COMPLETE,
                State.CANCELED,
            ):
                break
            time.sleep(0.2)
        assert task is not None
        assert task.state().state == State.COMPLETE
        assert task.outcome() == Outcome.SUCCESS
    finally:
        engine.stop()
