"""Engine/task/queue/storage tests, mirroring the reference's
``pkg/task/{queue,storage,task}_test.go`` + supervisor behaviors."""

import time

import pytest

from testground_tpu.api import (
    BuildOutput,
    Composition,
    Global,
    Group,
    InstanceConstraints,
    Instances,
    RunOutput,
    TestCase,
    TestPlanManifest,
)
from testground_tpu.builders.base import Builder
from testground_tpu.config import EnvConfig
from testground_tpu.engine import (
    CreatedBy,
    Engine,
    EngineConfig,
    Outcome,
    QueueFullError,
    State,
    Task,
    TaskQueue,
    TaskStorage,
    TaskType,
)
from testground_tpu.engine.queue import QueueEmptyError
from testground_tpu.engine.task import DatedState, new_task_id
from testground_tpu.runners.base import Runner
from testground_tpu.runners.result import Result


def mktask(tid=None, priority=0, created=None, **kw):
    return Task(
        id=tid or new_task_id(),
        type=TaskType.RUN,
        priority=priority,
        states=[
            DatedState(state=State.SCHEDULED, created=created or time.time())
        ],
        **kw,
    )


class TestTaskModel:
    def test_ids_are_20_chars_and_sortable(self):
        """integration_tests/header.sh asserts run-id length == 20."""
        ids = [new_task_id() for _ in range(100)]
        assert all(len(i) == 20 for i in ids)
        assert len(set(ids)) == 100

    def test_state_machine(self):
        t = mktask()
        assert t.state().state == State.SCHEDULED
        t.states.append(DatedState(state=State.PROCESSING, created=time.time()))
        assert t.state().state == State.PROCESSING
        assert not t.is_canceled()
        assert t.outcome() == Outcome.UNKNOWN

    def test_outcome_mapping(self):
        """pkg/data/result.go:17-51 semantics."""
        t = mktask()
        t.states.append(DatedState(state=State.COMPLETE, created=time.time()))
        t.result = {"outcome": "success"}
        assert t.outcome() == Outcome.SUCCESS
        t.error = "boom"
        assert t.outcome() == Outcome.FAILURE
        t2 = mktask()
        t2.states.append(DatedState(state=State.CANCELED, created=time.time()))
        assert t2.outcome() == Outcome.CANCELED

    def test_round_trip(self):
        t = mktask(plan="p", case="c", composition={"global": {"plan": "p"}})
        t2 = Task.from_dict(t.to_dict())
        assert t2.to_dict() == t.to_dict()


class TestQueue:
    def test_priority_then_fifo(self):
        """queue.go:178-189: priority desc, then creation asc."""
        st = TaskStorage()
        q = TaskQueue(st, max_size=10)
        now = time.time()
        q.push(mktask("a" * 20, priority=0, created=now))
        q.push(mktask("b" * 20, priority=5, created=now + 1))
        q.push(mktask("c" * 20, priority=0, created=now + 2))
        assert q.pop().id == "b" * 20
        assert q.pop().id == "a" * 20
        assert q.pop().id == "c" * 20
        with pytest.raises(QueueEmptyError):
            q.pop()

    def test_bounded(self):
        st = TaskStorage()
        q = TaskQueue(st, max_size=2)
        q.push(mktask())
        q.push(mktask())
        with pytest.raises(QueueFullError):
            q.push(mktask())

    def test_rehydrates_from_storage(self, tmp_path):
        """queue.go:18-31: queue rebuilt from disk on restart, including
        tasks that were mid-processing."""
        db = str(tmp_path / "tasks.db")
        st = TaskStorage(db)
        q = TaskQueue(st, max_size=10)
        q.push(mktask("q" * 20))
        q.push(mktask("r" * 20))
        popped = q.pop()  # now in 'current' bucket
        st.close()

        st2 = TaskStorage(db)
        q2 = TaskQueue(st2, max_size=10)
        ids = {q2.pop().id, q2.pop().id}
        assert ids == {"q" * 20, "r" * 20}
        assert popped.id in ids

    def test_push_unique_by_branch(self):
        """queue.go:79-96: same repo+branch tasks are canceled on re-push."""
        st = TaskStorage()
        q = TaskQueue(st, max_size=10)
        cb = CreatedBy(user="ci", repo="org/repo", branch="main", commit="abc")
        old = mktask("o" * 20, created_by=cb)
        q.push_unique_by_branch(old)
        new = mktask("n" * 20, created_by=cb)
        q.push_unique_by_branch(new)
        assert len(q) == 1
        assert q.pop().id == "n" * 20
        archived = st.get("o" * 20)
        assert archived.state().state == State.CANCELED

    def test_cancel_queued(self):
        st = TaskStorage()
        q = TaskQueue(st, max_size=10)
        q.push(mktask("x" * 20))
        assert q.cancel_queued("x" * 20)
        assert not q.cancel_queued("x" * 20)
        assert st.get("x" * 20).state().state == State.CANCELED


class TestStorage:
    def test_lifecycle_buckets(self):
        st = TaskStorage()
        t = mktask("t" * 20)
        st.persist_scheduled(t)
        assert st.scheduled()[0].id == t.id
        st.persist_processing(t)
        assert st.scheduled() == []
        assert st.processing()[0].id == t.id
        st.archive(t)
        assert st.processing() == []
        assert st.archived()[0].id == t.id
        assert st.get(t.id).id == t.id

    def test_filter(self):
        st = TaskStorage()
        now = time.time()
        a = mktask("a" * 20, created=now - 100)
        b = mktask("b" * 20, created=now)
        st.persist_scheduled(a)
        st.archive(b)
        got = st.filter(states=["scheduled"])
        assert [t.id for t in got] == ["a" * 20]
        got = st.filter(before=now - 50)
        assert [t.id for t in got] == ["a" * 20]
        got = st.filter(limit=1)
        assert len(got) == 1


# ---------------------------------------------------------------- engine


class FakeBuilder(Builder):
    def __init__(self, bid="fake:builder"):
        self._id = bid
        self.builds = 0

    def id(self):
        return self._id

    def build(self, inp, ow, cancel):
        self.builds += 1
        return BuildOutput(
            builder_id=self._id, artifact_path=f"artifact-{self.builds}"
        )


class FakeRunner(Runner):
    def __init__(self, rid="fake:runner", outcome="success", delay=0.0):
        self._id = rid
        self._outcome = outcome
        self._delay = delay
        self.jobs = []

    def id(self):
        return self._id

    def compatible_builders(self):
        return ["fake:builder"]

    def run(self, job, ow, cancel):
        self.jobs.append(job)
        deadline = time.time() + self._delay
        while time.time() < deadline:
            if cancel.is_set():
                raise RuntimeError("canceled")
            time.sleep(0.01)
        r = Result.for_input(job)
        for g in job.groups:
            for _ in range(g.instances):
                g_outcome = Outcome(self._outcome)
                r.add_outcome(g.id, g_outcome)
        r.update_outcome()
        return RunOutput(run_id=job.run_id, result=r)


def make_engine(tg_home, runner=None, builder=None, workers=None):
    env = EnvConfig.load()
    if workers is not None:
        env.daemon.scheduler.workers = workers
    engine = Engine(
        EngineConfig(
            env=env,
            builders=[builder or FakeBuilder()],
            runners=[runner or FakeRunner()],
        )
    )
    return engine


def simple_composition(n=2):
    return Composition(
        global_=Global(
            plan="testplan",
            case="ok",
            builder="fake:builder",
            runner="fake:runner",
        ),
        groups=[Group(id="all", instances=Instances(count=n))],
    )


def simple_manifest():
    return TestPlanManifest(
        name="testplan",
        builders={"fake:builder": {}},
        runners={"fake:runner": {}},
        testcases=[
            TestCase(
                name="ok", instances=InstanceConstraints(minimum=1, maximum=100)
            )
        ],
    )


def wait_complete(engine, task_id, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        t = engine.get_task(task_id)
        if t is not None and t.state().state in (State.COMPLETE, State.CANCELED):
            return t
        time.sleep(0.02)
    raise TimeoutError(f"task {task_id} did not complete")


class TestEngineEndToEnd:
    def test_queue_run_processes_to_success(self, tg_home):
        from testground_tpu.api import generate_default_run

        engine = make_engine(tg_home)
        engine.start_workers()
        try:
            comp = generate_default_run(simple_composition())
            tid = engine.queue_run(comp, simple_manifest())
            t = wait_complete(engine, tid)
            assert t.outcome() == Outcome.SUCCESS
            assert t.result["outcomes"]["all"] == {"total": 2, "ok": 2}
            # artifact was built and recorded in the prepared composition
            comp_out = t.result["composition"]
            assert comp_out["groups"][0]["run"]["artifact"] == "artifact-1"
        finally:
            engine.stop()

    def test_failure_outcome(self, tg_home):
        from testground_tpu.api import generate_default_run

        engine = make_engine(tg_home, runner=FakeRunner(outcome="failure"))
        engine.start_workers()
        try:
            tid = engine.queue_run(
                generate_default_run(simple_composition()), simple_manifest()
            )
            t = wait_complete(engine, tid)
            assert t.outcome() == Outcome.FAILURE
        finally:
            engine.stop()

    def test_incompatible_builder_rejected(self, tg_home):
        """engine.go:216-219 compat check at queue time (itest
        run_test.go: incompatible builder/runner must be rejected)."""
        from testground_tpu.api import generate_default_run

        engine = make_engine(tg_home)
        comp = generate_default_run(simple_composition())
        comp.global_.builder = "docker:other"
        comp.groups[0].builder = "docker:other"
        with pytest.raises(ValueError, match="incompatible"):
            engine.queue_run(comp, simple_manifest())

    def test_kill_running_task(self, tg_home):
        from testground_tpu.api import generate_default_run

        engine = make_engine(tg_home, runner=FakeRunner(delay=30))
        engine.start_workers()
        try:
            tid = engine.queue_run(
                generate_default_run(simple_composition()), simple_manifest()
            )
            # wait until it starts processing
            deadline = time.time() + 5
            while time.time() < deadline:
                t = engine.get_task(tid)
                if t and t.state().state == State.PROCESSING:
                    break
                time.sleep(0.02)
            assert engine.kill(tid)
            t = wait_complete(engine, tid)
            assert t.outcome() in (Outcome.CANCELED, Outcome.FAILURE)
        finally:
            engine.stop()

    def test_build_dedup_across_identical_groups(self, tg_home):
        """supervisor.go:359-364: two groups with the same build key build
        once."""
        from testground_tpu.api import generate_default_run

        builder = FakeBuilder()
        engine = make_engine(tg_home, builder=builder)
        engine.start_workers()
        try:
            comp = simple_composition()
            comp.groups = [
                Group(id="g1", instances=Instances(count=1)),
                Group(id="g2", instances=Instances(count=1)),
            ]
            comp = generate_default_run(comp)
            tid = engine.queue_run(comp, simple_manifest())
            t = wait_complete(engine, tid)
            assert t.outcome() == Outcome.SUCCESS
            assert builder.builds == 1
        finally:
            engine.stop()

    def test_disabled_runner_refused(self, tg_home):
        """supervisor.go:568-571 + integration test 18."""
        from testground_tpu.api import generate_default_run

        (tg_home / ".env.toml").write_text(
            '[runners."fake:runner"]\ndisabled = true\n'
        )
        engine = make_engine(tg_home)
        engine.start_workers()
        try:
            tid = engine.queue_run(
                generate_default_run(simple_composition()), simple_manifest()
            )
            t = wait_complete(engine, tid)
            assert t.outcome() == Outcome.FAILURE
            assert "disabled" in t.error
        finally:
            engine.stop()

    def test_logs_capture_run_output(self, tg_home):
        from testground_tpu.api import generate_default_run

        engine = make_engine(tg_home)
        engine.start_workers()
        try:
            tid = engine.queue_run(
                generate_default_run(simple_composition()), simple_manifest()
            )
            wait_complete(engine, tid)
            lines = list(engine.logs(tid))
            assert any('"t": "r"' in l or '"t":"r"' in l for l in lines)
        finally:
            engine.stop()

    def test_multi_run_composition(self, tg_home):
        """[[runs]] multi-run support (integration 1493_*)."""
        from testground_tpu.api import CompositionRunGroup, Run

        engine = make_engine(tg_home)
        engine.start_workers()
        try:
            comp = simple_composition()
            comp.runs = [
                Run(id="r1", groups=[CompositionRunGroup(id="all")]),
                Run(id="r2", groups=[CompositionRunGroup(id="all")]),
            ]
            tid = engine.queue_run(comp, simple_manifest())
            t = wait_complete(engine, tid)
            assert t.outcome() == Outcome.SUCCESS
            assert set(t.result["runs"].keys()) == {"r1", "r2"}
        finally:
            engine.stop()


class TestConcurrentWorkers:
    """The worker pool under load: many tasks across several workers, with
    kills landing mid-flight (the reference's 2-worker default pool,
    ``engine.go:120-122``, exercised far past its normal cadence)."""

    def test_many_tasks_drain_with_correct_outcomes(self, tg_home):
        from testground_tpu.api import generate_default_run

        ok_runner = FakeRunner(delay=0.05)
        engine = make_engine(tg_home, runner=ok_runner, workers=4)
        engine.start_workers()
        try:
            ids = [
                engine.queue_run(
                    generate_default_run(simple_composition()),
                    simple_manifest(),
                    sources_dir="",
                )
                for _ in range(12)
            ]
            tasks = [wait_complete(engine, tid, timeout=30) for tid in ids]
            assert all(t.outcome() == Outcome.SUCCESS for t in tasks)
            # every task ran exactly one runner job; nothing was lost or
            # double-dispatched across the 4 workers
            assert len(ok_runner.jobs) == 12
            assert len({j.run_id for j in ok_runner.jobs}) == 12
        finally:
            engine.stop()

    def test_kills_mid_flight_do_not_disturb_others(self, tg_home):
        from testground_tpu.api import generate_default_run

        slow = FakeRunner(delay=5.0)
        engine = make_engine(tg_home, runner=slow, workers=3)
        engine.start_workers()
        try:
            ids = [
                engine.queue_run(
                    generate_default_run(simple_composition()),
                    simple_manifest(),
                    sources_dir="",
                )
                for _ in range(3)
            ]
            # let them all get picked up, then kill the middle one
            deadline = time.time() + 10
            while time.time() < deadline and len(slow.jobs) < 3:
                time.sleep(0.02)
            # all three must actually be mid-flight, else this silently
            # degrades into a queued-cancel test
            assert len(slow.jobs) == 3
            assert engine.kill(ids[1]) is True
            killed = wait_complete(engine, ids[1], timeout=10)
            assert killed.outcome() == Outcome.CANCELED
            # the kill is fast; the survivors keep running to success
            for tid in (ids[0], ids[2]):
                t = wait_complete(engine, tid, timeout=30)
                assert t.outcome() == Outcome.SUCCESS
        finally:
            engine.stop()
