"""Control-plane tracing + fleet observability (docs/OBSERVABILITY.md
"Control plane"): causal task-lifecycle spans, the daemon event journal,
``GET /fleet`` / ``GET /events``, the ``tg_fleet_*`` Prometheus family,
and ``tg top``.

Pins the acceptance contracts:

- one submitted task traces end-to-end as a SINGLE connected span tree
  (every parent_id resolves; the root is the submitter's ``submit``
  span; run spans from the executor join under ``execute``);
- pack members share ONE claim span; a solo-despite-pack run carries
  ``solo_reason`` on its claim span;
- the event journal is ordered (monotonic seq, across rotation) and
  tails over ``GET /events`` with auth + 404 semantics;
- the fleet Prometheus gauges aggregate over the FULL task store:
  Σ ``tg_fleet_tasks`` == store count even past the per-task-series
  truncation limit;
- lifecycle tracing is zero-overhead for the jitted loop: the chunk
  jaxpr is identical and no host syncs are added.
"""

import json
import os
import re
import time

import pytest

from testground_tpu.api import generate_default_run
from testground_tpu.config import EnvConfig
from testground_tpu.daemon import Daemon
from testground_tpu.engine import Outcome, State
from testground_tpu.engine.events import EventJournal
from testground_tpu.engine.tracetree import (
    TASK_SPANS_FILE,
    TASK_TRACE_FILE,
    lifecycle_spans,
    load_task_spans,
)
from testground_tpu.tracectx import TraceContext, parse_traceparent
from tests.test_engine import (
    make_engine,
    mktask,
    simple_composition,
    simple_manifest,
    wait_complete,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


def assert_connected(spans):
    """Every parent_id resolves to another span in the same file and
    exactly one root exists — the tree-connectivity contract."""
    ids = {s["span_id"] for s in spans}
    assert len(ids) == len(spans), "duplicate span ids"
    roots = [s for s in spans if not s["parent_id"]]
    assert len(roots) == 1, [s["name"] for s in roots]
    for s in spans:
        assert s["parent_id"] == "" or s["parent_id"] in ids, s
    return roots[0]


# ------------------------------------------------------------ trace ctx


class TestTraceContext:
    def test_traceparent_round_trip(self):
        ctx = TraceContext.mint()
        header = ctx.to_traceparent()
        assert re.fullmatch(
            r"00-[0-9a-f]{32}-[0-9a-f]{16}-01", header
        )
        parsed = parse_traceparent(header)
        assert parsed == (ctx.trace_id, ctx.span_id)

    def test_invalid_headers_rejected(self):
        for bad in (
            "",
            "garbage",
            "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # zero trace id
            "00-" + "1" * 32 + "-" + "0" * 16 + "-01",  # zero span id
            "00-xyz-abc-01",
        ):
            assert parse_traceparent(bad) is None
        # an invalid header restarts the trace rather than failing
        ctx = TraceContext.from_traceparent("garbage")
        assert ctx is None

    def test_child_keeps_trace_id(self):
        ctx = TraceContext.mint()
        kid = ctx.child()
        assert kid.trace_id == ctx.trace_id
        assert kid.parent_id == ctx.span_id
        assert kid.span_id != ctx.span_id


# ----------------------------------------------------- lifecycle e2e


class TestLifecycleTraceE2E:
    def test_submitted_task_exports_connected_tree(self, tg_home):
        """The tentpole pin: submit with a client-minted traceparent,
        archive, and the exported tree is singly-rooted at the
        submitter's span with every parent resolving."""
        engine = make_engine(tg_home)
        engine.start_workers()
        try:
            ctx = TraceContext.mint()
            tid = engine.queue_run(
                generate_default_run(simple_composition()),
                simple_manifest(),
                trace_parent=ctx.to_traceparent(),
            )
            t = wait_complete(engine, tid)
            assert t.outcome() == Outcome.SUCCESS
            # the trace rides the task record (and survives to_dict)
            assert t.trace["trace_id"] == ctx.trace_id
            assert t.trace["root_span_id"] == ctx.span_id
            assert t.to_dict()["trace"]["trace_id"] == ctx.trace_id

            run_dir = os.path.join(
                engine.env.dirs.outputs(), t.plan, t.id
            )
            spans = load_task_spans(
                os.path.join(run_dir, TASK_SPANS_FILE)
            )
            root = assert_connected(spans)
            assert root["name"] == "submit"
            assert root["span_id"] == ctx.span_id
            names = {s["name"] for s in spans}
            assert {"submit", "queued", "claim", "execute"} <= names
            # Perfetto sibling exists and is well-formed trace-event JSON
            trace = json.load(
                open(os.path.join(run_dir, TASK_TRACE_FILE))
            )
            assert len(trace["traceEvents"]) == len(spans)
        finally:
            engine.stop()

    def test_invalid_traceparent_restarts_trace(self, tg_home):
        engine = make_engine(tg_home)
        engine.start_workers()
        try:
            tid = engine.queue_run(
                generate_default_run(simple_composition()),
                simple_manifest(),
                trace_parent="not-a-traceparent",
            )
            t = wait_complete(engine, tid)
            # a fresh trace was minted — the tree still exports connected
            assert re.fullmatch(r"[0-9a-f]{32}", t.trace["trace_id"])
            spans = load_task_spans(
                os.path.join(
                    engine.env.dirs.outputs(),
                    t.plan,
                    t.id,
                    TASK_SPANS_FILE,
                )
            )
            assert_connected(spans)
        finally:
            engine.stop()

    def test_queued_secs(self, tg_home):
        engine = make_engine(tg_home)
        engine.start_workers()
        try:
            tid = engine.queue_run(
                generate_default_run(simple_composition()),
                simple_manifest(),
            )
            t = wait_complete(engine, tid)
            assert t.queued_secs() >= 0.0
            # a still-queued task reports live wait
            q = mktask(created=time.time() - 2.0)
            assert q.queued_secs() >= 1.5
        finally:
            engine.stop()

    def test_sim_run_spans_join_the_tree(self, tg_home):
        """Executor SpanTracer rows (run_spans.jsonl) carry the task's
        trace_id and parent under the execute span — the whole
        submit→chunk tree is one connected trace."""
        from testground_tpu.builders.sim_plan import SimPlanBuilder
        from testground_tpu.sim.runner import SimJaxRunner
        from tests.test_sim_runner import run_sim

        engine = make_engine(
            tg_home, runner=SimJaxRunner(), builder=SimPlanBuilder()
        )
        engine.start_workers()
        try:
            ctx = TraceContext.mint()
            orig = engine.queue_run

            def traced_queue_run(*a, **kw):
                kw.setdefault("trace_parent", ctx.to_traceparent())
                return orig(*a, **kw)

            engine.queue_run = traced_queue_run
            t = run_sim(
                engine,
                "network",
                "ping-pong",
                instances=2,
                run_params={"chunk": 16},
            )
            assert t.outcome() == Outcome.SUCCESS
            spans = load_task_spans(
                os.path.join(
                    engine.env.dirs.outputs(),
                    "network",
                    t.id,
                    TASK_SPANS_FILE,
                )
            )
            root = assert_connected(spans)
            assert root["span_id"] == ctx.span_id
            run_rows = [s for s in spans if s["kind"] == "run"]
            assert run_rows, "no executor spans joined the tree"
            assert all(
                s["trace_id"] == ctx.trace_id for s in run_rows
            )
            # the executor's `run` span hangs off the execute span
            execute = next(s for s in spans if s["name"] == "execute")
            top_run = next(s for s in run_rows if s["name"] == "run")
            assert top_run["parent_id"] == execute["span_id"]
            # every run row stamps a wall clock
            assert all(s["start_ns"] > 0 for s in run_rows)
        finally:
            engine.stop()


# ----------------------------------------------------------- pack spans


class TestPackClaimSpan:
    def test_pack_members_share_one_claim_span(self, tg_home):
        from testground_tpu.engine.supervisor import _note_claim

        engine = make_engine(tg_home)
        try:
            a, b = mktask(), mktask()
            _note_claim(engine, 0, [a, b])
            assert a.trace["claim_span_id"] == b.trace["claim_span_id"]
            assert (
                a.trace["execute_span_id"] != b.trace["execute_span_id"]
            )
            assert a.trace["pack_leader"] == a.id
            assert b.trace["pack_leader"] == a.id
            assert a.trace["pack_width"] == 2
            fi = engine.fleet_info()
            assert fi["pack"]["packed"] == 1
            assert fi["pack"]["packed_runs"] == 2
            # both claims landed in the histograms
            assert sum(fi["claim_latency_bins"]) == 2
            # the claim span renders pack attrs in each member's tree
            a.states.append(
                type(a.states[0])(
                    state=State.PROCESSING, created=time.time()
                )
            )
            spans = lifecycle_spans(a)
            claim = next(s for s in spans if s["name"] == "claim")
            assert claim["pack_width"] == 2
            assert claim["span_id"] == b.trace["claim_span_id"]
        finally:
            engine.stop()

    def test_solo_reason_rides_the_claim_span(self, tg_home):
        engine = make_engine(tg_home)
        try:
            t = mktask()
            from testground_tpu.engine.supervisor import _note_claim

            _note_claim(engine, 0, [t])
            t.trace["solo_reason"] = "width cap"
            engine.fleet_note_solo("width cap")
            t.states.append(
                type(t.states[0])(
                    state=State.PROCESSING, created=time.time()
                )
            )
            claim = next(
                s
                for s in lifecycle_spans(t)
                if s["name"] == "claim"
            )
            assert claim["solo_reason"] == "width cap"
            assert "pack_leader" not in claim
            assert engine.fleet_info()["pack"]["solo"] == {
                "width cap": 1
            }
        finally:
            engine.stop()


# -------------------------------------------------------- event journal


class TestEventJournal:
    def test_ordering_and_rotation(self, tmp_path):
        path = str(tmp_path / "daemon_events.jsonl")
        j = EventJournal(path, max_bytes=600)
        for i in range(20):
            j.emit("task.scheduled", task=f"t{i}", n=i)
        assert os.path.exists(path + ".1"), "no rotation happened"
        rows = [json.loads(l) for l in open(path + ".1")] + [
            json.loads(l) for l in open(path)
        ]
        seqs = [r["seq"] for r in rows]
        # monotonic ACROSS the rotation boundary, no resets
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        last = rows[-1]
        assert last["type"] == "task.scheduled"
        assert last["ts_wall_ns"] > 0 and last["ts_mono_ns"] > 0

    def test_trace_ids_ride_events(self, tmp_path):
        j = EventJournal(str(tmp_path / "ev.jsonl"))
        trace = {"trace_id": "a" * 32, "claim_span_id": "b" * 16}
        j.emit("task.claimed", task="t1", trace=trace)
        row = json.loads(open(j.path).read())
        assert row["trace_id"] == "a" * 32
        assert row["span_id"] == "b" * 16

    def test_emit_never_raises(self, tmp_path):
        j = EventJournal(str(tmp_path / "ev.jsonl"))
        j.emit("x", weird=object())  # non-serializable attr → swallowed
        j.path = str(tmp_path / "no" / "such" / "dir" / "ev.jsonl")
        j.emit("y")  # unwritable path → swallowed

    def test_engine_emits_lifecycle_events_in_order(self, tg_home):
        engine = make_engine(tg_home)
        engine.start_workers()
        try:
            tid = engine.queue_run(
                generate_default_run(simple_composition()),
                simple_manifest(),
            )
            wait_complete(engine, tid)
            rows = [
                json.loads(l) for l in open(engine.events.path)
            ]
            types = [r["type"] for r in rows if r["task"] == tid]
            assert types.index("task.scheduled") < types.index(
                "task.claimed"
            )
            assert types.index("task.claimed") < types.index(
                "task.started"
            )
            assert types[-1] == "task.finished"
            tids = {r["trace_id"] for r in rows if r["task"] == tid}
            assert len(tids) == 1 and "" not in tids
        finally:
            engine.stop()

    def test_operator_kill_is_journaled(self, tg_home):
        engine = make_engine(tg_home)  # workers NOT started: stays queued
        try:
            tid = engine.queue_run(
                generate_default_run(simple_composition()),
                simple_manifest(),
            )
            assert engine.kill(tid)
            types = [
                json.loads(l)["type"]
                for l in open(engine.events.path)
            ]
            assert "task.cancel_requested" in types
            assert "task.canceled" in types
        finally:
            engine.stop()


# --------------------------------------------------- daemon HTTP routes


@pytest.fixture()
def daemon(tg_home):
    d = Daemon(env=EnvConfig.load(), listen="localhost:0")
    d.start()
    yield d
    d.stop()


@pytest.fixture()
def client(daemon):
    from testground_tpu.client import Client

    return Client(daemon.address)


class TestDaemonFleetRoutes:
    def test_events_404_before_first_event(self, client):
        from testground_tpu.client import DaemonError

        with pytest.raises(DaemonError, match="no events journal"):
            list(client.events())

    def test_fleet_events_and_artifact_over_http(self, client):
        """One placebo run through the daemon with a traceparent header:
        /fleet reflects it, /events tails it with a resumable offset,
        and /artifact serves the exported span tree."""
        assert client.import_plan(
            os.path.join(PLANS, "placebo")
        ) == "placebo"
        ctx = TraceContext.mint()
        task_id = client.run(
            {
                "metadata": {"name": "placebo-ok"},
                "global": {
                    "plan": "placebo",
                    "case": "ok",
                    "builder": "exec:py",
                    "runner": "local:exec",
                    "total_instances": 1,
                },
                "groups": [{"id": "all", "instances": {"count": 1}}],
            },
            trace_parent=ctx.to_traceparent(),
        )
        deadline = time.time() + 60
        while time.time() < deadline:
            t = client.status(task_id)
            if t["states"][-1]["state"] in ("complete", "canceled"):
                break
            time.sleep(0.2)
        assert t["outcome"] == "success"
        # the daemon minted the tree from the HTTP traceparent header
        assert t["trace"]["trace_id"] == ctx.trace_id
        assert t["trace"]["root_span_id"] == ctx.span_id

        fleet = client.fleet()
        assert fleet["tasks_total"] >= 1
        assert fleet["counts"].get("complete", 0) >= 1
        assert set(fleet["workers"]) == {"total", "busy", "idle"}

        rows = list(client.events())
        assert rows[-1]["type"] == "_tail"
        offset = rows[-1]["offset"]
        types = [r["type"] for r in rows[:-1]]
        assert "task.scheduled" in types and "task.finished" in types
        # resume from the trailer's offset: nothing new
        again = list(client.events(since=offset))
        assert [r for r in again if r["type"] != "_tail"] == []

        raw = client.artifact(task_id, TASK_SPANS_FILE)
        spans = [
            json.loads(l) for l in raw.decode().splitlines() if l
        ]
        root = assert_connected(spans)
        assert root["span_id"] == ctx.span_id

    def test_events_bad_since_and_auth(self, tg_home):
        from testground_tpu.client import Client, DaemonError

        env = EnvConfig.load()
        env.daemon.tokens = ["sekrit"]
        d = Daemon(env=env, listen="localhost:0")
        d.start()
        try:
            with pytest.raises(DaemonError, match="unauthorized"):
                Client(d.address).fleet()
            with pytest.raises(DaemonError, match="unauthorized"):
                list(Client(d.address).events())
            ok = Client(d.address, token="sekrit")
            assert ok.fleet()["tasks_total"] == 0
            with pytest.raises(DaemonError, match="invalid since"):
                list(ok._get_stream("/events", {"since": "xyz"}))
        finally:
            d.stop()


# ----------------------------------------------------------- prometheus


class TestFleetPrometheus:
    LINE_RE = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? "
        r"-?[0-9.e+-]+(\.[0-9]+)?$|^[a-zA-Z_:][a-zA-Z0-9_:]*"
        r"(\{[^{}]*\})? \+Inf$"
    )

    def _tasks(self, n):
        out = []
        for i in range(n):
            t = mktask()
            if i % 3 == 0:
                t.states.append(
                    type(t.states[0])(
                        state=State.PROCESSING, created=time.time()
                    )
                )
            out.append(t)
        return out

    def test_conservation_over_full_store(self, tg_home):
        """The fleet-total-blindness fix: Σ tg_fleet_tasks == store
        count even when the per-task series truncate at 200."""
        from testground_tpu.metrics.prometheus import render_prometheus

        tasks = self._tasks(250)
        text = render_prometheus(tasks, per_task_limit=200)
        states = dict(
            re.findall(r'tg_fleet_tasks\{state="(\w+)"\} (\d+)', text)
        )
        assert sum(int(v) for v in states.values()) == 250
        assert "tg_scrape_tasks_elided 50" in text
        # queue depth by priority covers every scheduled task
        prio = re.findall(
            r'tg_fleet_queue_depth\{priority="(-?\d+)"\} (\d+)', text
        )
        assert sum(int(v) for _, v in prio) == int(
            states.get("scheduled", 0)
        )

    def test_fleet_block_and_histograms_render(self, tg_home):
        from testground_tpu.metrics.prometheus import render_prometheus

        engine = make_engine(tg_home)
        try:
            engine.fleet_note_claim(0.001, 0.0005)
            engine.fleet_note_claim(2.0, 0.1)
            engine.fleet_note_pack("leader", 2)
            engine.fleet_note_solo("width cap")
            text = render_prometheus([], fleet=engine.fleet_info())
            for line in text.strip().splitlines():
                if line.startswith("#"):
                    continue
                assert self.LINE_RE.match(line), line
            for family in (
                "tg_fleet_workers",
                "tg_fleet_pack_admissions_total",
                "tg_fleet_pack_runs_total",
                "tg_fleet_pack_solo_total",
                "tg_fleet_queue_wait_seconds_bucket",
                "tg_fleet_queue_wait_seconds_sum",
                "tg_fleet_claim_latency_seconds_count",
            ):
                assert family in text, family
            assert 'reason="width cap"' in text
            # histogram buckets are cumulative and end at +Inf == count
            buckets = re.findall(
                r'tg_fleet_queue_wait_seconds_bucket\{le="([^"]+)"\} '
                r"(\d+)",
                text,
            )
            counts = [int(c) for _, c in buckets]
            assert counts == sorted(counts)
            assert buckets[-1][0] == "+Inf" and counts[-1] == 2
        finally:
            engine.stop()


# ----------------------------------------------------------------- CLI


class TestTgTopCLI:
    def test_top_no_follow_json(self, tg_home, capsys):
        from testground_tpu.cli.main import main

        assert main(["top", "--no-follow", "--json"]) == 0
        out = capsys.readouterr().out.strip()
        payload = json.loads(out)
        assert set(payload["workers"]) == {"total", "busy", "idle"}
        assert payload["tasks_total"] == 0

    def test_top_no_follow_rendered(self, tg_home, capsys):
        from testground_tpu.cli.main import main

        assert main(["top", "--no-follow"]) == 0
        out = capsys.readouterr().out
        assert "workers" in out and "queue depth" in out

    def test_trace_lifecycle_renders_tree(self, tg_home, capsys):
        """`tg trace --lifecycle` against the in-process disk engine
        reads the archived span tree from the outputs dir."""
        from testground_tpu.runners.pretty import render_lifecycle_tree

        spans = [
            {
                "name": "submit",
                "trace_id": "t" * 32,
                "span_id": "a",
                "parent_id": "",
                "start_ns": 0,
                "end_ns": 3_000_000,
                "kind": "lifecycle",
            },
            {
                "name": "queued",
                "trace_id": "t" * 32,
                "span_id": "b",
                "parent_id": "a",
                "start_ns": 0,
                "end_ns": 1_000_000,
                "kind": "lifecycle",
            },
            {
                "name": "orphan",
                "trace_id": "t" * 32,
                "span_id": "c",
                "parent_id": "missing",
                "start_ns": 2,
                "end_ns": 2,
                "kind": "point",
            },
        ]
        out = render_lifecycle_tree(spans)
        assert "submit" in out and "  queued" in out
        assert "orphan subtree" in out  # broken trees are visible


# -------------------------------------------------------- zero overhead


class TestZeroOverhead:
    def test_trace_ctx_does_not_shape_the_program(self):
        """Lifecycle tracing is host-side bookkeeping: the chunk jaxpr
        is identical whether or not a trace context exists, and the
        SpanTracer's id stamping adds no host syncs to the jitted
        loop."""
        import jax

        from tests.test_sim_perf import pingpong_prog
        from testground_tpu.sim import engine as engine_mod
        from testground_tpu.sim.telemetry import SpanTracer

        a, b = pingpong_prog(), pingpong_prog()
        carry = jax.eval_shape(lambda: a.init_carry(0))
        assert str(jax.make_jaxpr(a._chunk_step)(carry)) == str(
            jax.make_jaxpr(b._chunk_step)(carry)
        )

        calls = {"n": 0}
        real = engine_mod._poll_done

        def counting(done):
            calls["n"] += 1
            return real(done)

        def run(tmpdir, ctx):
            calls["n"] = 0
            tracer = SpanTracer(
                os.path.join(tmpdir, "run_spans.jsonl"), ctx=ctx
            )
            tracer.start("run")
            res = pingpong_prog().run(max_ticks=128)
            tracer.point("chunk", ticks=int(res["ticks"]))
            tracer.end("run", outcome="success")
            tracer.close()
            return calls["n"], res

        import unittest.mock as mock

        with mock.patch.object(engine_mod, "_poll_done", counting):
            import tempfile

            with tempfile.TemporaryDirectory() as d1:
                n_off, res_off = run(d1, None)
            with tempfile.TemporaryDirectory() as d2:
                n_on, res_on = run(
                    d2,
                    {
                        "trace_id": "c" * 32,
                        "parent_id": "d" * 16,
                    },
                )
        assert n_on == n_off
        assert res_on["ticks"] == res_off["ticks"]

    def test_span_rows_carry_ids_and_wall_ns(self, tmp_path):
        from testground_tpu.sim.telemetry import SpanTracer

        path = str(tmp_path / "run_spans.jsonl")
        ctx = {"trace_id": "e" * 32, "parent_id": "f" * 16}
        tr = SpanTracer(path, ctx=ctx)
        tr.start("run")
        tr.start("build")
        tr.point("chunk", ticks=16)
        tr.end("build")
        tr.end("run", outcome="success")
        tr.close()
        from testground_tpu.sdk.events import parse_event_line

        events = [
            parse_event_line(l)[1] for l in open(path)
        ]
        assert all(e["trace_id"] == "e" * 32 for e in events)
        assert all(e["wall_ns"] > 0 for e in events)
        run_start = next(
            e
            for e in events
            if e["type"] == "span_start" and e["span"] == "run"
        )
        build_start = next(
            e
            for e in events
            if e["type"] == "span_start" and e["span"] == "build"
        )
        point = next(e for e in events if e["type"] == "point")
        # nesting: run hangs off the injected parent, build and the
        # chunk point hang off the innermost open span
        assert run_start["parent_id"] == "f" * 16
        assert build_start["parent_id"] == run_start["span_id"]
        assert point["parent_id"] == build_start["span_id"]
        run_end = next(
            e
            for e in events
            if e["type"] == "span_end" and e["span"] == "run"
        )
        assert run_end["span_id"] == run_start["span_id"]


# --------------------------------------------------- sync hello add-ons


class TestSyncHelloAttribution:
    def test_task_ops_block_is_additive_and_bounded(self):
        from testground_tpu.sync.stats import PARITY_FIELDS, SyncStats

        st = SyncStats()
        st.task_ops_batch({"run-a": 3, "run-b": 2})
        st.task_ops_batch({"run-a": 1})
        snap = st.snapshot()
        assert snap["tasks"] == {"run-a": 4, "run-b": 2}
        # additive: the parity contract is untouched
        assert "tasks" not in PARITY_FIELDS
        # bounded: overflow aggregates under "" and Σ conserves
        st2 = SyncStats()
        for i in range(80):
            st2.task_ops_batch({f"r{i:03d}": 1})
        tasks = st2.snapshot()["tasks"]
        assert len(tasks) <= 65
        assert sum(tasks.values()) == 80
        assert tasks[""] == 80 - 64

    def test_server_attributes_ops_to_hello_task(self):
        from testground_tpu.sync.client import SyncClient
        from testground_tpu.sync.server import SyncServiceServer

        srv = SyncServiceServer().start()
        try:
            host, port = srv.address
            c = SyncClient(
                host,
                port,
                namespace="run:r1:",
                identity={
                    "events_topic": "run:r1:events",
                    "group": "g",
                    "instance": 0,
                    "task": "r1",
                },
            )
            c.signal_entry("s")
            c.signal_entry("s")
            deadline = time.time() + 2
            while time.time() < deadline:
                snap = srv.stats.snapshot()
                if snap.get("tasks", {}).get("r1", 0) >= 2:
                    break
                time.sleep(0.02)
            assert snap["tasks"]["r1"] >= 2
            c.close()
        finally:
            srv.stop()
