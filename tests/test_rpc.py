"""RPC chunk protocol tests (``pkg/rpc/rpc_test.go`` semantics)."""

import base64
import io

from testground_tpu.rpc import (
    CHUNK_BINARY,
    CHUNK_ERROR,
    CHUNK_PROGRESS,
    CHUNK_RESULT,
    Chunk,
    OutputWriter,
    discard_writer,
    parse_chunks,
)


def test_progress_result_stream():
    sink = io.StringIO()
    ow = OutputWriter(sink=sink)
    ow.infof("hello %s", "world")
    ow.write_result({"outcome": "success"})

    chunks = list(parse_chunks(io.StringIO(sink.getvalue())))
    assert [c.type for c in chunks] == [CHUNK_PROGRESS, CHUNK_RESULT]
    assert "hello world" in chunks[0].payload
    assert chunks[1].payload == {"outcome": "success"}


def test_error_chunk():
    sink = io.StringIO()
    ow = OutputWriter(sink=sink)
    ow.write_error("boom")
    (c,) = parse_chunks(io.StringIO(sink.getvalue()))
    assert c.type == CHUNK_ERROR
    assert c.error == "boom"


def test_binary_chunks_round_trip():
    sink = io.StringIO()
    ow = OutputWriter(sink=sink)
    data = bytes(range(256)) * 300
    ow.write_binary(io.BytesIO(data), chunk_size=1000)
    chunks = list(parse_chunks(io.StringIO(sink.getvalue())))
    assert all(c.type == CHUNK_BINARY for c in chunks)
    assert len(chunks) > 1
    recovered = b"".join(base64.b64decode(c.payload) for c in chunks)
    assert recovered == data


def test_chunk_json_round_trip():
    for c in (
        Chunk(type=CHUNK_PROGRESS, payload="text\n"),
        Chunk(type=CHUNK_RESULT, payload={"k": [1, 2]}),
        Chunk(type=CHUNK_ERROR, error="msg"),
    ):
        c2 = Chunk.from_json(c.to_json())
        assert (c2.type, c2.payload, c2.error) == (c.type, c.payload, c.error)


def test_discard_writer_is_silent():
    ow = discard_writer()
    ow.infof("nothing")
    ow.write_result(1)  # must not raise
