"""A second no-SDK language speaking the instance protocol end to end.

The reference proves multi-language via JS/Rust plans with shell e2e
coverage (``plans/example-js``, ``integration_tests/
example_02_js_pingpong.sh``); here the Perl plan ``plans/example-perl``
is implemented from ``docs/INSTANCE_PROTOCOL.md`` alone — TEST_* env,
stdout event lines, sync TCP barriers/pubsub with interleaved reply
matching, REAL inter-instance TCP ping-pong traffic, and the run-events
outcome publish — and must pass the same outcome/collection assertions
as any SDK plan."""

import os
import shutil
import tarfile

import pytest

from testground_tpu.cli.main import main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")

pytestmark = pytest.mark.skipif(
    shutil.which("perl") is None, reason="no perl interpreter"
)


def _run(instances, rounds=3):
    assert main(["plan", "import", "--from", os.path.join(PLANS, "example-perl")]) == 0
    return main(
        [
            "run", "single", "example-perl:pingpong",
            "--builder", "exec:bin",
            "--runner", "local:exec",
            "-i", str(instances),
            "-tp", f"rounds={rounds}",
        ]
    )


class TestPerlPingPong:
    def test_pairs_exchange_real_traffic(self, tg_home, tmp_path, capsys):
        """4 instances pair up over sync pubsub, exchange 3 TCP ping/pong
        rounds each, and all report success (example_02_js_pingpong.sh
        analog: ``assert_run_outcome_is success``)."""
        rc = _run(instances=4)
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "(outcome: success)" in out
        # real rounds ran: the dialers printed RTT message lines
        assert out.count("round 3 rtt:") == 2  # one per pair
        # every instance's terminal event reached the outcome collector
        assert "4/4" in out

    def test_odd_instance_count_runs_solo(self, tg_home, tmp_path, capsys):
        """The unpaired instance must succeed solo, not hang a barrier
        (the sim edition's odd-instance contract, applied to real
        processes)."""
        rc = _run(instances=3)
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "runs solo" in out
        assert "3/3" in out

    def test_collection_layout(self, tg_home, tmp_path, capsys):
        """tg collect returns the reference outputs layout
        (<plan>/<run>/<group>/<instance>/ — ``local_docker.go:258-267``)
        for a no-SDK plan too."""
        rc = _run(instances=2)
        out = capsys.readouterr().out
        assert rc == 0
        run_id = out.split("finished run with ID:")[1].split()[0]
        tgz = str(tmp_path / "out.tgz")
        assert main(["collect", run_id, "-o", tgz]) == 0
        capsys.readouterr()
        with tarfile.open(tgz, "r:gz") as tar:
            names = tar.getnames()
        # both instance dirs present under the group
        assert any("/single/0" in n for n in names), names
        assert any("/single/1" in n for n in names), names

    def test_polyglot_cross_language_rendezvous(self, tg_home, tmp_path, capsys):
        """Python and Perl instances in ONE run (mixed builders: exec:py
        group + exec:bin group of the same plan) coordinate through the
        same sync service — shared enrolled/done barriers at the full
        cross-group count and a shared pubsub topic where every instance
        sees every peer's language. The reference's multi-language story
        is per-plan; this proves the instance protocol interoperates
        ACROSS languages inside one run."""
        assert (
            main(["plan", "import", "--from", os.path.join(PLANS, "polyglot")])
            == 0
        )
        comp = tmp_path / "poly.toml"
        comp.write_text(
            """
[metadata]
name = "polyglot-rendezvous"

[global]
plan = "polyglot"
case = "rendezvous"
builder = "exec:py"
runner = "local:exec"

[[groups]]
id = "pythons"
builder = "exec:py"
[groups.instances]
count = 2

[[groups]]
id = "perls"
builder = "exec:bin"
[groups.instances]
count = 2
"""
        )
        capsys.readouterr()
        rc = main(["run", "composition", "-f", str(comp)])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "(outcome: success)" in out
        # both languages enrolled and each saw BOTH languages at the
        # rendezvous (the topic carried cross-language entries)
        assert "python instance enrolled" in out
        assert "perl instance enrolled" in out
        assert out.count("rendezvous of perl+python complete") == 4

    def test_failure_propagates(self, tg_home, tmp_path, capsys):
        """An unknown case makes every instance publish a failure event;
        the run outcome must be failure (silent-failure guard,
        ``14_test_silent_failure.sh`` analog)."""
        assert (
            main(["plan", "import", "--from", os.path.join(PLANS, "example-perl")])
            == 0
        )
        rc = main(
            [
                "run", "single", "example-perl:nope",
                "--builder", "exec:bin",
                "--runner", "local:exec",
                "-i", "2",
            ]
        )
        out = capsys.readouterr().out
        assert rc == 1
        assert "(outcome: failure)" in out
