"""Optional InfluxDB metrics push (reference: the SDK batches runtime
metrics to InfluxDB via ``INFLUXDB_URL``, ``pkg/runner/local_docker.go:353``;
here ``[daemon] influxdb_endpoint`` mirrors the run's timeseries rows to
``POST /write?db=testground`` in line protocol)."""

import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from testground_tpu.metrics.influx import push_rows, rows_to_lines

ROWS = [
    {
        "run": "r1",
        "plan": "network",
        "case": "ping-pong",
        "tick": 128,
        "group_id": "all",
        "name": "rtt_ticks",
        "count": 10,
        "mean": 5.5,
    },
    {
        "run": "r1",
        "plan": "network",
        "case": "ping-pong",
        "tick": 256,
        "group_id": "g 2",
        "name": "rtt_ticks",
        "count": 11,
        "mean": 6.5,
    },
]


class TestLineProtocol:
    def test_rows_to_lines(self):
        lines = rows_to_lines(ROWS)
        assert lines[0] == (
            "results.network-ping-pong.rtt_ticks,run=r1,group_id=all"
            " count=10i,mean=5.5,tick=128i 128"
        )
        # tag values with spaces are escaped, ints get the i suffix
        assert r"group_id=g\ 2" in lines[1]
        assert "count=11i" in lines[1]

    def test_base_ns_offsets_timestamps(self):
        """push_rows passes wall-clock time as base_ns so points land in
        Grafana's default now-6h window; tick stays both an offset (point
        ordering within a series) and an integer field (plottable)."""
        base = 1_700_000_000_000_000_000
        lines = rows_to_lines(ROWS, base_ns=base)
        assert lines[0].endswith(f" {base + 128}")
        assert lines[1].endswith(f" {base + 256}")
        assert "tick=128i" in lines[0]

    def test_rows_without_name_or_fields_skipped(self):
        assert rows_to_lines([{"run": "r", "tick": 1}]) == []
        assert (
            rows_to_lines(
                [{"name": "m", "plan": "p", "case": "c", "tick": 1, "note": "x"}]
            )
            == []
        )

    def test_non_finite_drops_are_collected(self):
        """Dropping a NaN/Inf field must not be silent: the collector
        names the lost <measurement>.<field>, while plain non-field
        values (strings) stay uncounted — they were never metrics."""
        dropped = []
        rows_to_lines(
            [
                {
                    "plan": "p",
                    "case": "c",
                    "name": "m",
                    "tick": 0,
                    "ratio": float("inf"),
                    "note": "not-a-field",
                    "count": 3,
                }
            ],
            dropped=dropped,
        )
        assert dropped == ["results.p-c.m.ratio"]

    def test_non_finite_fields_are_dropped(self):
        """inf/nan are invalid line protocol; a single bad field must not
        poison the batch (the POST carries every line of the run)."""
        lines = rows_to_lines(
            [
                {
                    "plan": "p",
                    "case": "c",
                    "name": "m",
                    "tick": 0,
                    "ratio": float("inf"),
                    "count": 3,
                },
                {
                    "plan": "p",
                    "case": "c",
                    "name": "m2",
                    "tick": 0,
                    "bad": float("nan"),
                },
            ]
        )
        assert lines == ["results.p-c.m count=3i,tick=0i 0"]

    def test_measurement_escaping(self):
        lines = rows_to_lines(
            [
                {
                    "plan": "p p",
                    "case": "c",
                    "name": "m",
                    "tick": 0,
                    "count": 1,
                }
            ]
        )
        assert lines[0].startswith(r"results.p\ p-c.m ")


class _CaptureHandler(BaseHTTPRequestHandler):
    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        self.server.captured.append((self.path, self.rfile.read(n).decode()))
        self.send_response(204)
        self.end_headers()

    def log_message(self, *a):  # noqa: D102 — quiet
        pass


@pytest.fixture()
def influx_server():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _CaptureHandler)
    srv.captured = []
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield srv
    finally:
        srv.shutdown()
        srv.server_close()


class TestPush:
    def test_push_rows(self, influx_server):
        endpoint = f"http://127.0.0.1:{influx_server.server_address[1]}"
        journal = push_rows(endpoint, ROWS)
        assert journal == {"pushed": 2, "ok": True, "attempts": 1}
        path, body = influx_server.captured[0]
        assert path == "/write?db=testground"
        assert body.count("\n") == 2
        assert "results.network-ping-pong.rtt_ticks" in body

    def test_push_empty_is_ok_and_sends_nothing(self, influx_server):
        endpoint = f"http://127.0.0.1:{influx_server.server_address[1]}"
        assert push_rows(endpoint, []) == {"pushed": 0, "ok": True}
        assert influx_server.captured == []

    def test_push_journals_dropped_non_finite_fields(self, influx_server):
        """A NaN/Inf field is dropped from the batch AND journaled (with
        a logged warning) — never silently lost, never a 400 for the
        whole single-POST batch."""
        endpoint = f"http://127.0.0.1:{influx_server.server_address[1]}"
        rows = [
            dict(ROWS[0], bad=float("nan")),
            dict(ROWS[1], worse=float("inf")),
        ]
        journal = push_rows(endpoint, rows)
        assert journal["ok"] is True
        assert journal["dropped_field_count"] == 2
        assert journal["dropped_fields"] == [
            "results.network-ping-pong.rtt_ticks.bad",
            "results.network-ping-pong.rtt_ticks.worse",
        ]
        body = influx_server.captured[0][1]
        assert "bad" not in body and "worse" not in body

    def test_push_failure_is_journaled_not_raised(self, monkeypatch):
        from testground_tpu.metrics import influx as influx_mod

        monkeypatch.setattr(influx_mod, "_RETRY_BASE_SECS", 0.0)
        monkeypatch.setattr(influx_mod, "_RETRY_JITTER_SECS", 0.0)
        journal = push_rows("http://127.0.0.1:1", ROWS, timeout=0.5)
        assert journal["ok"] is False
        assert "error" in journal
        # the FINAL failure journals how hard the mirror was tried
        assert journal["attempts"] == influx_mod._RETRY_ATTEMPTS

    def test_push_retries_transient_5xx_then_succeeds(self, monkeypatch):
        """A transient server error must not lose the batch: bounded
        retries with backoff recover once the endpoint heals, and the
        journal records the attempt count."""
        from testground_tpu.metrics import influx as influx_mod

        monkeypatch.setattr(influx_mod, "_RETRY_BASE_SECS", 0.0)
        monkeypatch.setattr(influx_mod, "_RETRY_JITTER_SECS", 0.0)

        class FlakyHandler(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                self.server.hits += 1
                self.send_response(
                    500 if self.server.hits <= 2 else 204
                )
                self.end_headers()

            def log_message(self, *a):
                pass

        srv = ThreadingHTTPServer(("127.0.0.1", 0), FlakyHandler)
        srv.hits = 0
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            endpoint = f"http://127.0.0.1:{srv.server_address[1]}"
            journal = push_rows(endpoint, ROWS)
        finally:
            srv.shutdown()
            srv.server_close()
        assert journal["ok"] is True
        assert journal["attempts"] == 3
        assert srv.hits == 3
        assert "error" not in journal

    def test_push_4xx_is_permanent_no_retry(self, monkeypatch):
        """A 400 (malformed lines) won't improve with waiting — one
        attempt, journaled as the final failure."""
        from testground_tpu.metrics import influx as influx_mod

        monkeypatch.setattr(influx_mod, "_RETRY_BASE_SECS", 0.0)

        class RejectHandler(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                self.server.hits += 1
                self.send_response(400)
                self.end_headers()

            def log_message(self, *a):
                pass

        srv = ThreadingHTTPServer(("127.0.0.1", 0), RejectHandler)
        srv.hits = 0
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            endpoint = f"http://127.0.0.1:{srv.server_address[1]}"
            journal = push_rows(endpoint, ROWS)
        finally:
            srv.shutdown()
            srv.server_close()
        assert journal["ok"] is False
        assert journal["attempts"] == 1
        assert srv.hits == 1
        assert journal["error"] == "http 400"

    def test_stable_base_ns_makes_repushes_idempotent(self, influx_server):
        """ADVICE r4: a retried push with the run's stable base_ns must
        produce byte-identical line-protocol (same timestamps), so Influx
        overwrites points instead of duplicating them; per-call wall
        clocks would re-stamp every retry."""
        endpoint = f"http://127.0.0.1:{influx_server.server_address[1]}"
        push_rows(endpoint, ROWS, base_ns=1_700_000_000_000_000_000)
        push_rows(endpoint, ROWS, base_ns=1_700_000_000_000_000_000)
        (_, body1), (_, body2) = influx_server.captured
        assert body1 == body2


class TestSimRunPush:
    def test_sim_run_mirrors_timeseries_to_influx(self, tg_home, influx_server):
        """End-to-end: a sim:jax run under an env with influxdb_endpoint
        configured pushes its sampled rows and journals the result."""
        from tests.test_sim_runner import run_sim
        from testground_tpu.builders.sim_plan import SimPlanBuilder
        from testground_tpu.config import EnvConfig
        from testground_tpu.engine import Engine, EngineConfig, Outcome
        from testground_tpu.sim.runner import SimJaxRunner

        endpoint = f"http://127.0.0.1:{influx_server.server_address[1]}"
        with open(os.path.join(tg_home, ".env.toml"), "w") as f:
            f.write(f'[daemon]\ninfluxdb_endpoint = "{endpoint}"\n')
        env = EnvConfig.load()
        e = Engine(
            EngineConfig(
                env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
            )
        )
        e.start_workers()
        try:
            t = run_sim(e, "benchmarks", "netinit", instances=8)
        finally:
            e.stop()
        assert t.outcome() == Outcome.SUCCESS
        assert t.result["journal"]["influx"]["ok"] is True
        assert t.result["journal"]["influx"]["pushed"] > 0
        body = influx_server.captured[0][1]
        assert "results.benchmarks-netinit.time_to_network_init_ticks" in body

    def test_sim_telemetry_family_is_mirrored(self, tg_home, influx_server):
        """With telemetry on, the per-tick sim.* measurement family goes
        to Influx alongside the plan metrics (docs/OBSERVABILITY.md) —
        the same expanded shape the dashboard renders."""
        from tests.test_sim_runner import run_sim
        from testground_tpu.builders.sim_plan import SimPlanBuilder
        from testground_tpu.config import EnvConfig
        from testground_tpu.engine import Engine, EngineConfig, Outcome
        from testground_tpu.sim.runner import SimJaxRunner

        endpoint = f"http://127.0.0.1:{influx_server.server_address[1]}"
        with open(os.path.join(tg_home, ".env.toml"), "w") as f:
            f.write(f'[daemon]\ninfluxdb_endpoint = "{endpoint}"\n')
        env = EnvConfig.load()
        e = Engine(
            EngineConfig(
                env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
            )
        )
        e.start_workers()
        try:
            t = run_sim(
                e,
                "network",
                "ping-pong",
                instances=2,
                run_params={"telemetry": True, "chunk": 16},
            )
        finally:
            e.stop()
        assert t.outcome() == Outcome.SUCCESS
        # the sim family pushes in its own bounded batches, separate
        # from (and after) the plan-metric batch — scan every POST
        assert t.result["journal"]["influx"]["ok"] is True
        assert t.result["journal"]["influx_telemetry"]["ok"] is True
        assert t.result["journal"]["influx_telemetry"]["batches"] >= 1
        body = "\n".join(b for _, b in influx_server.captured)
        delivered = [
            l
            for l in body.splitlines()
            if l.startswith("results.network-ping-pong.sim.delivered,")
        ]
        live = [
            l
            for l in body.splitlines()
            if l.startswith("results.network-ping-pong.sim.live,")
        ]
        assert delivered and all(",group_id=_run " in l for l in delivered)
        assert live and all(",group_id=all " in l for l in live)
        # per-tick sim rows: one line per counter per tick
        assert len(delivered) == t.result["journal"]["telemetry"]["rows"]
        # plan metrics went in their own first batch, unmixed with sim.*
        first = influx_server.captured[0][1]
        assert "pingpong.rtt" in first and "sim." not in first
