"""``runners/pretty.py`` table rendering under hostile payloads
(satellite of the perf-ledger PR): the stats and perf tables are fed
decoded JSON from the daemon or hand-rolled clients, so missing, zero,
None and NaN fields must degrade to readable placeholders — never a
TypeError, never a misleading blank."""

import math

from testground_tpu.runners.pretty import (
    render_perf_summary,
    render_telemetry_summary,
)

NAN = float("nan")


class TestTelemetrySummaryRobustness:
    def test_empty_payload(self):
        out = render_telemetry_summary({})
        assert "no telemetry recorded" in out

    def test_missing_sim_fields_render_placeholders(self):
        out = render_telemetry_summary(
            {"plan": "p", "case": "c", "sim": {"msgs_delivered": 1}}
        )
        # absent wall/compile render as '?', not as fake zeros or a crash
        assert "?s (compile ?s)" in out
        assert "delivered=1" in out

    def test_none_and_nan_fields(self):
        out = render_telemetry_summary(
            {
                "plan": "p",
                "case": "c",
                "sim": {
                    "ticks": None,
                    "tick_ms": NAN,
                    "wall_secs": None,
                    "compile_secs": NAN,
                    "devices": None,
                    "carry_bytes": NAN,
                    "msgs_delivered": 2,
                },
            }
        )
        assert "nan" not in out.lower()
        assert "?" in out
        # a NaN carry must drop the line, not print a bogus size
        assert "device-resident" not in out

    def test_zero_values_still_render(self):
        out = render_telemetry_summary(
            {
                "plan": "p",
                "case": "c",
                "sim": {
                    "ticks": 0,
                    "tick_ms": 1.0,
                    "wall_secs": 0.0,
                    "compile_secs": 0.0,
                    "msgs_delivered": 0,
                },
            }
        )
        assert "0 (0.00 sim-s at 1 ms/tick)" in out
        assert "delivered=0" in out

    def test_latency_with_nan_count(self):
        out = render_telemetry_summary(
            {
                "plan": "p",
                "case": "c",
                "sim": {
                    "ticks": 1,
                    "tick_ms": 1.0,
                    "latency": {"g0": {"count": NAN}},
                },
            }
        )
        assert "latency g0" in out and "no deliveries" in out

    def test_perf_teaser_line(self):
        out = render_telemetry_summary(
            {
                "plan": "p",
                "case": "c",
                "sim": {
                    "ticks": 8,
                    "tick_ms": 1.0,
                    "perf": {
                        "execute": {"steady_peer_ticks_per_sec": 1234.0}
                    },
                },
            }
        )
        assert "1,234 peer·ticks/s" in out

    def test_perf_teaser_skipped_when_nan(self):
        out = render_telemetry_summary(
            {
                "plan": "p",
                "case": "c",
                "sim": {
                    "ticks": 8,
                    "tick_ms": 1.0,
                    "perf": {"execute": {"peer_ticks_per_sec": NAN}},
                },
            }
        )
        assert "peer·ticks/s" not in out


class TestPerfSummaryRobustness:
    FULL = {
        "task_id": "t1",
        "plan": "network",
        "case": "ping-pong",
        "outcome": "success",
        "sim": {"compile_secs": 1.2, "carry_bytes": 4096},
        "perf": {
            "instances": 2,
            "chunk": 16,
            "compile": {
                "lower_secs": 0.4,
                "compile_secs": 0.7,
                "flops": 4872.0,
                "bytes_accessed": 69231.0,
                "argument_bytes": 12568,
                "temp_bytes": 15584,
                "generated_code_bytes": 0,
                "peak_bytes": 28152,
            },
            "execute": {
                "chunks": 14,
                "ticks": 224,
                "wall_secs": 0.14,
                "ticks_per_sec": 1580.0,
                "peer_ticks_per_sec": 3161.0,
                "steady_chunks": 13,
                "steady_ticks_per_sec": 13450.0,
                "steady_peer_ticks_per_sec": 26901.0,
                "est_flops_per_sec": 4.1e6,
            },
            "hbm": {"peak_bytes": 3 << 30, "bytes_limit": 16 << 30},
            "series": {"rows": 14, "file": "sim_perf.jsonl"},
        },
        "task": {"queued_secs": 0.2, "runner_wall_secs": {"r1": 1.4}},
    }

    def test_full_payload_prints_every_section(self):
        out = render_perf_summary(self.FULL)
        for fragment in (
            "AOT lower 0.40s + xla 0.70s",  # compile split
            "peer·ticks/s",  # throughput
            "26.90k",  # steady rate
            "flops",  # cost analysis
            "high-water 3.00 GiB of 16.00 GiB",  # HBM mark
            "queued 0.20s",  # supervisor timings
            "sim_perf.jsonl",  # series pointer
        ):
            assert fragment in out, fragment

    def test_empty_payload(self):
        out = render_perf_summary({"plan": "p", "case": "c"})
        assert "no performance ledger recorded" in out

    def test_ledgerless_payload_still_renders_scheduler_timings(self):
        # a multi-run composition journals per-run results (no top-level
        # sim/perf), but the supervisor's queue/runner walls are present
        # and must not be swallowed by the no-ledger message
        out = render_perf_summary(
            {
                "plan": "p",
                "case": "c",
                "task": {
                    "queued_secs": 0.25,
                    "runner_wall_secs": {"r1": 3.5, "r2": 4.5},
                },
            }
        )
        assert "no performance ledger recorded" in out
        assert "multi-run composition" in out
        assert "queued 0.25s" in out
        assert "run r1 3.50s" in out and "run r2 4.50s" in out

    def test_large_counts_render_verbatim_not_scientific(self):
        # '{:g}' would print 12345678 as '1.23457e+07' — tick totals
        # reach 1e6+ routinely, so counts must render losslessly
        out = render_perf_summary(
            {
                "plan": "p",
                "case": "c",
                "sim": {"compile_secs": 1.0},
                "perf": {
                    "instances": 100000,
                    "execute": {
                        "ticks": 12345678,
                        "wall_secs": 10.0,
                        "chunks": 1234567,
                    },
                },
            }
        )
        assert "12345678 ticks" in out
        assert "1234567 chunk(s)" in out
        assert "100000 instance(s)" in out
        assert "e+" not in out
        tele = render_telemetry_summary(
            {"plan": "p", "case": "c", "sim": {"ticks": 12345678, "tick_ms": 1.0}}
        )
        assert "12345678" in tele and "e+" not in tele

    def test_missing_hbm_says_so(self):
        payload = {
            "plan": "p",
            "case": "c",
            "sim": {"compile_secs": 1.0},
            "perf": {"execute": {"ticks": 8, "wall_secs": 1.0}},
        }
        out = render_perf_summary(payload)
        assert "no memory stats on this backend" in out

    def test_none_nan_and_zero_fields(self):
        payload = {
            "task_id": "x",
            "plan": "p",
            "case": "c",
            "sim": {"compile_secs": None, "carry_bytes": NAN},
            "perf": {
                "instances": None,
                "compile": {"lower_secs": NAN, "compile_secs": None},
                "execute": {
                    "ticks": NAN,
                    "wall_secs": 0,
                    "chunks": None,
                    "ticks_per_sec": math.inf,
                    "peer_ticks_per_sec": None,
                },
                "hbm": {"peak_bytes": NAN},
                "series": {"rows": 0},
            },
            "task": {"queued_secs": NAN, "runner_wall_secs": {"r1": None}},
        }
        out = render_perf_summary(payload)
        assert "nan" not in out.lower()
        assert "inf" not in out.lower()
        assert "?" in out
        # NaN HBM degrades to the unavailable line, zero rows drop series
        assert "no memory stats on this backend" in out
        assert "sim_perf.jsonl" not in out

    def test_absent_cost_analysis_drops_cost_line(self):
        payload = {
            "plan": "p",
            "case": "c",
            "sim": {"compile_secs": 1.0},
            "perf": {
                "instances": 2,
                "compile": {"lower_secs": 0.1, "compile_secs": 0.2},
                "execute": {
                    "chunks": 2,
                    "ticks": 16,
                    "wall_secs": 0.1,
                    "ticks_per_sec": 160.0,
                    "peer_ticks_per_sec": 320.0,
                },
            },
        }
        out = render_perf_summary(payload)
        assert "cost" not in out.splitlines()[0]
        assert not any(
            line.startswith("cost") for line in out.splitlines()
        )
        assert "AOT lower 0.10s + xla 0.20s" in out
