"""Checkpoint/resume plane (docs/CHECKPOINT.md): durable live-sim
snapshots with bit-identical continuation.

The load-bearing contracts pinned here:

1. **Determinism pin**: a run interrupted at an arbitrary chunk and
   resumed from its snapshot produces results identical LEAF FOR LEAF to
   an uninterrupted run — status, finished_at, every state leaf, every
   flow total, the latency histogram — on both the xla and pallas
   (interpret) transports, through the real on-disk snapshot format.
2. **Refuse loudly, never resume garbage**: corrupted/truncated archives,
   missing manifests, version drift, composition/transport mismatches
   and program-shape drift all raise the typed :class:`CheckpointError`.
3. **Zero overhead when off**: `checkpoint_chunks=0` leaves the host-sync
   count (and the program — the knob is not program-shaping) unchanged;
   armed checkpointing adds no `_poll_done` syncs either (the snapshot
   read is a direct transfer at K-chunk boundaries).
4. **The surface end to end**: executor resume (cross-run and in-place
   auto-resume) with byte-equal telemetry streams, journal
   `sim.checkpoint`, bounded retention, `tg stats` line, Prometheus
   `tg_checkpoint_*`, GET /artifact whitelist, and `tg run resume`.
"""

import json
import os
import threading

import numpy as np
import pytest

import jax

from testground_tpu.api import RunGroup, RunInput
from testground_tpu.config import EnvConfig
from testground_tpu.rpc import OutputWriter
from testground_tpu.sim import engine as engine_mod
from testground_tpu.sim.checkpoint import (
    CHECKPOINT_DIR,
    CheckpointError,
    FORMAT_VERSION,
    list_snapshots,
    load_latest,
    load_snapshot,
    prune_snapshots,
    restore_carry,
    save_snapshot,
    snapshot_carry,
)
from testground_tpu.sim.engine import SimProgram, build_groups
from testground_tpu.sim.executor import (
    SimJaxConfig,
    execute_sim_run,
    load_sim_testcases,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")

# the observable-outcome keys compared leaf-for-leaf between a resumed
# and an uninterrupted run (the transport-equality discipline)
RESULT_KEYS = (
    "status",
    "finished_at",
    "ticks",
    "sync_counts",
    "pub_dropped",
    "latency_clamped",
    "bw_queue_dropped",
    "collisions",
    "msgs_delivered",
    "msgs_sent",
    "msgs_enqueued",
    "msgs_dropped",
    "msgs_rejected",
    "cal_depth",
    "faults_crashed",
    "faults_restarted",
    "fault_dropped",
)


def make_groups(*counts):
    return build_groups(
        [
            RunGroup(id=f"g{i}", instances=c, parameters={})
            for i, c in enumerate(counts)
        ]
    )


def pingpong_prog(n=4, chunk=16, transport="xla", telemetry=True):
    tc = load_sim_testcases(os.path.join(PLANS, "network"))["ping-pong"]()
    return SimProgram(
        tc,
        make_groups(n),
        chunk=chunk,
        telemetry=telemetry,
        transport=transport,
    )


def assert_results_equal(res_a, res_b, label=""):
    for key in RESULT_KEYS:
        a, b = np.asarray(res_a[key]), np.asarray(res_b[key])
        assert np.array_equal(a, b), f"[{label}] {key}: {a} vs {b}"
    la, ta = jax.tree.flatten(res_a["states"])
    lb, tb = jax.tree.flatten(res_b["states"])
    assert ta == tb, f"[{label}] state structure drifted"
    for i, (a, b) in enumerate(zip(la, lb)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            f"[{label}] state leaf {i} differs"
        )
    assert res_a.get("lat_hist") == res_b.get("lat_hist"), (
        f"[{label}] latency histogram differs"
    )


# ------------------------------------------------------------ file format


class TestSnapshotFormat:
    def _leaves(self):
        key = jax.random.key(7)
        carry = {
            "a": np.arange(12, dtype=np.int32).reshape(3, 4),
            "b": (np.ones((2,), np.float32), jax.random.split(key, 5)),
        }
        return snapshot_carry(carry)

    def test_roundtrip_including_prng_keys(self, tmp_path):
        leaves, metas = self._leaves()
        assert [m["kind"] for m in metas] == ["array", "array", "prng"]
        manifest = {
            "version": FORMAT_VERSION,
            "tick": 32,
            "leaves": metas,
            "aux": {},
        }
        path, size, ms = save_snapshot(str(tmp_path), manifest, leaves)
        assert os.path.basename(path) == "ckpt-000000000032.npz"
        assert size == os.path.getsize(path) and size > 0
        m2, leaves2 = load_snapshot(path)
        assert m2["tick"] == 32 and m2["leaves"] == metas
        for a, b in zip(leaves, leaves2):
            assert np.array_equal(a, b) and a.dtype == b.dtype

    def test_atomic_no_tmp_left_and_listing_ignores_foreign(self, tmp_path):
        leaves, metas = self._leaves()
        for tick in (64, 16, 48):
            save_snapshot(
                str(tmp_path),
                {
                    "version": FORMAT_VERSION,
                    "tick": tick,
                    "leaves": metas,
                    "aux": {},
                },
                leaves,
            )
        d = tmp_path / CHECKPOINT_DIR
        # foreign noise + a fake in-flight temp file must be invisible
        (d / "notes.txt").write_text("x")
        (d / "ckpt-000000000064.npz.tmp-999").write_text("partial")
        assert not [p for p in os.listdir(d) if p.endswith(f".tmp-{os.getpid()}")]
        snaps = list_snapshots(str(tmp_path))
        assert [t for t, _ in snaps] == [16, 48, 64]  # tick-ordered

    def test_retention_keeps_newest(self, tmp_path):
        leaves, metas = self._leaves()
        for tick in (16, 32, 48, 64, 80):
            save_snapshot(
                str(tmp_path),
                {
                    "version": FORMAT_VERSION,
                    "tick": tick,
                    "leaves": metas,
                    "aux": {},
                },
                leaves,
            )
        removed = prune_snapshots(str(tmp_path), keep=2)
        assert removed == 3
        assert [t for t, _ in list_snapshots(str(tmp_path))] == [64, 80]

    def test_truncated_archive_refuses_typed(self, tmp_path):
        leaves, metas = self._leaves()
        path, size, _ = save_snapshot(
            str(tmp_path),
            {
                "version": FORMAT_VERSION,
                "tick": 8,
                "leaves": metas,
                "aux": {},
            },
            leaves,
        )
        with open(path, "r+b") as f:
            f.truncate(size // 2)
        with pytest.raises(CheckpointError, match="corrupt or truncated"):
            load_snapshot(path)

    def test_garbage_bytes_refuse_typed(self, tmp_path):
        p = tmp_path / "ckpt-000000000001.npz"
        p.write_bytes(b"this is not a zip archive at all")
        with pytest.raises(CheckpointError):
            load_snapshot(str(p))

    def test_archive_without_manifest_refuses(self, tmp_path):
        p = tmp_path / "ckpt-000000000002.npz"
        np.savez(str(p), leaf_00000=np.zeros(3))
        with pytest.raises(CheckpointError, match="no embedded manifest"):
            load_snapshot(str(p))

    def test_version_drift_refuses(self, tmp_path):
        leaves, metas = self._leaves()
        path, _, _ = save_snapshot(
            str(tmp_path),
            {
                "version": FORMAT_VERSION + 1,
                "tick": 8,
                "leaves": metas,
                "aux": {},
            },
            leaves,
        )
        with pytest.raises(CheckpointError, match="format version"):
            load_snapshot(path)

    def test_missing_leaf_refuses(self, tmp_path):
        leaves, metas = self._leaves()
        path, _, _ = save_snapshot(
            str(tmp_path),
            {
                "version": FORMAT_VERSION,
                "tick": 8,
                # manifest promises one more leaf than the archive holds
                "leaves": metas + [{"kind": "array", "shape": [1], "dtype": "int32"}],
                "aux": {},
            },
            leaves,
        )
        with pytest.raises(CheckpointError, match="missing carry leaf"):
            load_snapshot(path)

    def test_load_latest_empty_dir_refuses(self, tmp_path):
        with pytest.raises(CheckpointError, match="no snapshots"):
            load_latest(str(tmp_path))


# ----------------------------------------------------- restore validation


class TestRestoreValidation:
    def test_wrong_composition_shape_refuses(self, tmp_path):
        prog4 = pingpong_prog(n=4)
        carry = jax.jit(lambda: prog4.init_carry(0))()
        leaves, metas = snapshot_carry(carry)
        manifest = {"leaves": metas}
        # a program built for a DIFFERENT instance count must refuse
        prog8 = pingpong_prog(n=8)
        with pytest.raises(CheckpointError, match="refusing to resume"):
            restore_carry(prog8, 0, manifest, leaves)

    def test_cross_transport_layout_refuses(self):
        # xla keeps flat calendar planes, pallas keeps 2-D rows: a
        # snapshot from one cannot silently seed the other
        prog_x = pingpong_prog(n=4, transport="xla")
        carry = jax.jit(lambda: prog_x.init_carry(0))()
        leaves, metas = snapshot_carry(carry)
        prog_p = pingpong_prog(n=4, transport="pallas")
        with pytest.raises(CheckpointError):
            restore_carry(prog_p, 0, {"leaves": metas}, leaves)

    def test_kind_drift_refuses(self):
        prog = pingpong_prog(n=2)
        carry = jax.jit(lambda: prog.init_carry(0))()
        leaves, metas = snapshot_carry(carry)
        bad = [dict(m) for m in metas]
        # claim the first PRNG leaf is a plain array
        for m in bad:
            if m["kind"] == "prng":
                m["kind"] = "array"
                break
        with pytest.raises(CheckpointError):
            restore_carry(prog, 0, {"leaves": bad}, leaves)


# ------------------------------------------------ determinism pin (engine)


class TestEngineResumeDeterminism:
    @pytest.mark.parametrize("transport", ["xla", "pallas"])
    def test_kill_at_chunk_resume_equals_uninterrupted(
        self, tmp_path, transport
    ):
        """THE acceptance pin: interrupt at a chunk boundary, persist
        through the real archive format, restore into a freshly built
        program, continue — and compare every leaf against an
        uninterrupted run. On CPU the pallas arm runs the real kernels
        in interpret mode."""
        cut = 48  # an arbitrary mid-run chunk boundary (chunk=16)
        res_full = pingpong_prog(transport=transport).run(
            seed=3, max_ticks=512
        )
        assert res_full["ticks"] > cut  # the cut is genuinely mid-run

        prog_cut = pingpong_prog(transport=transport)
        captured = {}

        def observer(ticks, carry):
            if ticks == cut:
                captured["leaves"], captured["metas"] = snapshot_carry(
                    carry
                )

        prog_cut.run(seed=3, max_ticks=cut, observer=observer)
        path, _, _ = save_snapshot(
            str(tmp_path),
            {
                "version": FORMAT_VERSION,
                "tick": cut,
                "leaves": captured["metas"],
                "aux": {},
            },
            captured["leaves"],
        )
        manifest, leaves = load_snapshot(path)

        prog_res = pingpong_prog(transport=transport)
        carry = restore_carry(prog_res, 3, manifest, leaves)
        res_res = prog_res.run(
            seed=3, max_ticks=512, resume_carry=carry, resume_ticks=cut
        )
        assert_results_equal(res_full, res_res, label=transport)


# ----------------------------------------------------------- zero overhead


class TestZeroOverhead:
    def test_program_jaxpr_untouched_by_checkpointing(self):
        """The knob is not program-shaping: the chunk program traced for
        a checkpointed run is the IDENTICAL jaxpr — snapshotting rides
        the observer hook, never the compiled tick. (Guards against a
        future change threading a checkpoint flag into SimProgram.)"""
        prog = pingpong_prog(n=2, chunk=8)
        carry = jax.jit(lambda: prog.init_carry(0))()
        before = str(jax.make_jaxpr(prog._chunk_step)(carry))
        # run WITH an armed checkpointing observer over the same program
        prog.run(
            seed=0,
            max_ticks=16,
            observer=lambda ticks, c: snapshot_carry(c),
        )
        carry2 = jax.jit(lambda: prog.init_carry(0))()
        after = str(jax.make_jaxpr(prog._chunk_step)(carry2))
        assert before == after

    def test_sync_count_unchanged_by_checkpoint_knob(
        self, tg_home, monkeypatch
    ):
        """The default program's one-blocking-sync-per-chunk contract is
        untouched by the knob at 0 AND by armed checkpointing (snapshot
        reads are direct transfers, never extra done-polls)."""
        counts = []
        real = engine_mod._poll_done

        def run_once(run_id, **cfg_kw):
            c = [0]

            def counting(done):
                c[0] += 1
                return real(done)

            monkeypatch.setattr(engine_mod, "_poll_done", counting)
            out = _exec(run_id, max_ticks=128, **cfg_kw)
            counts.append(c[0])
            return out

        run_once("sync-off")  # no knob at all
        run_once("sync-zero", checkpoint_chunks=0)
        run_once("sync-armed", checkpoint_chunks=1)
        assert counts[0] == counts[1] == counts[2]


# --------------------------------------------------------- executor e2e


def _exec(run_id, cancel=None, env=None, **cfg_kw):
    env = env or EnvConfig.load()
    cfg_kw.setdefault("chunk", 16)
    cfg_kw.setdefault("telemetry", True)
    cfg_kw.setdefault("netmatrix", True)
    cfg_kw.setdefault("seed", 5)
    cfg = SimJaxConfig(**cfg_kw)
    job = RunInput(
        run_id=run_id,
        test_plan="network",
        test_case="ping-pong",
        total_instances=4,
        groups=[
            RunGroup(
                id="single",
                instances=4,
                artifact_path=os.path.join(PLANS, "network"),
            )
        ],
        runner_config=cfg,
        env=env,
    )
    return execute_sim_run(
        job, OutputWriter(sink=None), cancel or threading.Event()
    )


def _series_rows(env, run_id, name="sim_timeseries.jsonl"):
    path = os.path.join(env.dirs.outputs(), "network", run_id, name)
    with open(path) as f:
        return [
            {k: v for k, v in json.loads(line).items() if k != "run"}
            for line in f
        ]


@pytest.fixture(scope="class")
def resumed_runs(tmp_path_factory):
    """One shared cut → resume → auto-resume sequence (compile once,
    assert many)."""
    home = tmp_path_factory.mktemp("tghome")
    old = os.environ.get("TESTGROUND_HOME")
    os.environ["TESTGROUND_HOME"] = str(home)
    try:
        env = EnvConfig.load()
        out = {
            "env": env,
            "full": _exec(
                "full", env=env, max_ticks=512, checkpoint_chunks=2
            ),
            "cut": _exec(
                "cut",
                env=env,
                max_ticks=64,
                checkpoint_chunks=2,
                checkpoint_keep=2,
            ),
        }
        out["res"] = _exec(
            "res",
            env=env,
            max_ticks=512,
            checkpoint_chunks=2,
            resume_from="cut",
        )
        # in-place auto-resume: the interrupted task re-runs under its
        # OWN id (the daemon-restart rehydration path) and continues
        # from its own newest snapshot
        out["auto"] = _exec(
            "cut", env=env, max_ticks=512, checkpoint_chunks=2
        )
        yield out
    finally:
        if old is None:
            os.environ.pop("TESTGROUND_HOME", None)
        else:
            os.environ["TESTGROUND_HOME"] = old


class TestExecutorResume:
    def test_cut_wrote_bounded_snapshots_and_journal(self, resumed_runs):
        env = resumed_runs["env"]
        ckpt_dir = os.path.join(
            env.dirs.outputs(), "network", "cut", CHECKPOINT_DIR
        )
        jc = resumed_runs["cut"].result.journal["sim"]["checkpoint"]
        assert jc["every_chunks"] == 2 and jc["count"] >= 2
        assert jc["last_tick"] == 64 and jc["bytes"] > 0
        assert jc["write_ms"] > 0 and jc["dir"] == CHECKPOINT_DIR
        # retention: checkpoint_keep=2 bounds what survives on disk
        # (the auto-resume run later continues with the default keep)
        names = sorted(os.listdir(ckpt_dir))
        assert all(n.startswith("ckpt-") and n.endswith(".npz") for n in names)
        assert len(names) <= 3

    def test_resumed_journal_equals_uninterrupted(self, resumed_runs):
        jf = resumed_runs["full"].result.journal
        for label in ("res", "auto"):
            jr = resumed_runs[label].result.journal
            for key in (
                "ticks",
                "msgs_delivered",
                "msgs_sent",
                "msgs_enqueued",
                "msgs_dropped",
                "msgs_rejected",
                "msgs_in_flight",
                "latency_clamped",
            ):
                assert jr["sim"][key] == jf["sim"][key], (label, key)
            assert jr["sim"].get("latency") == jf["sim"].get("latency")
            assert jr["telemetry"]["totals"] == jf["telemetry"]["totals"]
            assert jr["telemetry"]["rows"] == jf["telemetry"]["rows"]
            assert jr["events"] == jf["events"]

    def test_resumed_telemetry_stream_is_byte_equal(self, resumed_runs):
        env = resumed_runs["env"]
        rows_full = _series_rows(env, "full")
        assert rows_full, "reference run produced no telemetry rows"
        assert _series_rows(env, "res") == rows_full
        assert _series_rows(env, "cut") == rows_full  # in-place resume

    def test_resumed_netmatrix_stream_is_byte_equal(self, resumed_runs):
        """The traffic-matrix stream is resume-aligned like telemetry:
        a resumed run reproduces ``sim_netmatrix.jsonl`` row for row
        (the writer seeks to the cut's chunk count, never duplicates or
        skips a chunk delta)."""
        env = resumed_runs["env"]
        rows_full = _series_rows(env, "full", "sim_netmatrix.jsonl")
        assert rows_full, "reference run produced no netmatrix rows"
        # one row per chunk, ticks continue monotonically across resume
        assert [r["chunk"] for r in rows_full] == list(range(len(rows_full)))
        assert _series_rows(env, "res", "sim_netmatrix.jsonl") == rows_full
        assert _series_rows(env, "cut", "sim_netmatrix.jsonl") == rows_full

    def test_resumed_netmatrix_journal_equals_uninterrupted(
        self, resumed_runs
    ):
        """The host-side matrix accumulator is aux checkpoint state: a
        resume seeds it from the snapshot and lands on the exact same
        totals as the uninterrupted run — conservation intact."""
        nf = resumed_runs["full"].result.journal["sim"]["net_matrix"]
        assert nf["mismatches"] == []
        for label in ("res", "auto"):
            nr = resumed_runs[label].result.journal["sim"]["net_matrix"]
            assert nr["matrix"] == nf["matrix"], label
            assert nr["totals"] == nf["totals"], label
            assert nr["bytes_total"] == nf["bytes_total"], label
            assert nr["mismatches"] == [], label

    def test_resume_provenance_recorded(self, resumed_runs):
        jr = resumed_runs["res"].result.journal["sim"]["checkpoint"]
        assert jr["resumed"]["from_run"] == "cut"
        assert jr["resumed"]["from_tick"] == 64
        assert jr["resumed"]["snapshot"].startswith("ckpt-")
        ja = resumed_runs["auto"].result.journal["sim"]["checkpoint"]
        assert ja["resumed"]["from_run"] == "cut"

    def test_restart_mid_resume_prefers_own_newer_progress(
        self, resumed_runs
    ):
        """A daemon restart rehydrates a resume task with resume_from
        still set; it must continue from its OWN newest snapshot, not
        roll back to the (older) source snapshot and re-earn every tick
        — and must not overwrite its own streams with the source's
        shorter prefix."""
        env = resumed_runs["env"]
        rows_before = _series_rows(env, "res")
        out = _exec(
            "res",
            env=env,
            max_ticks=512,
            checkpoint_chunks=2,
            resume_from="cut",  # still set, as a rehydrated task has it
        )
        ck = out.result.journal["sim"]["checkpoint"]
        assert ck["resumed"]["from_run"] == "res"  # own, NOT "cut"
        assert ck["resumed"]["from_tick"] > 64  # past cut's newest
        jf = resumed_runs["full"].result.journal
        for key in ("msgs_delivered", "msgs_sent", "msgs_enqueued"):
            assert out.result.journal["sim"][key] == jf["sim"][key]
        # the stream was not rolled back to cut's prefix
        assert _series_rows(env, "res") == rows_before

    def test_stats_table_and_prometheus_surface(self, resumed_runs):
        from testground_tpu.engine.task import (
            DatedState,
            State,
            Task,
            TaskType,
        )
        from testground_tpu.metrics.prometheus import render_prometheus
        from testground_tpu.runners.pretty import render_telemetry_summary

        result = resumed_runs["res"].result.to_dict()
        t = Task(
            id="res",
            type=TaskType.RUN,
            plan="network",
            case="ping-pong",
            states=[DatedState(state=State.COMPLETE, created=0.0)],
            result=result,
        )
        table = render_telemetry_summary(t.stats_payload())
        assert "checkpoint" in table
        assert "resumed from tick 64 of run cut" in table
        text = render_prometheus([t], per_task_limit=10)
        for gauge in (
            "tg_checkpoint_count{",
            "tg_checkpoint_last_tick{",
            "tg_checkpoint_bytes{",
            "tg_checkpoint_write_ms{",
        ):
            assert gauge in text, f"{gauge} missing from exposition"

    def test_artifact_whitelist_serves_snapshots_only_safely(self):
        from testground_tpu.daemon.server import _Handler

        rel = _Handler._artifact_relpath
        assert rel("checkpoints/ckpt-000000000064.npz") == os.path.join(
            "checkpoints", "ckpt-000000000064.npz"
        )
        assert rel("checkpoints/../secrets.npz") is None
        assert rel("checkpoints/evil.npz") is None
        assert rel("checkpoints/ckpt-1/extra.npz") is None
        assert rel("ckpt-000000000064.npz") is None

    def test_resume_from_unknown_run_refuses(self, resumed_runs):
        with pytest.raises(CheckpointError, match="nothing to resume"):
            _exec(
                "res-none",
                env=resumed_runs["env"],
                max_ticks=64,
                resume_from="no-such-run",
            )

    def test_identity_mismatch_refuses(self, resumed_runs):
        # a different seed is a different deterministic stream: the
        # snapshot manifest must refuse to seed it
        with pytest.raises(CheckpointError, match="different run identity"):
            _exec(
                "res-seed",
                env=resumed_runs["env"],
                max_ticks=512,
                resume_from="cut",
                seed=6,
            )

    def test_corrupted_snapshot_fallback_then_refusal(
        self, resumed_runs, monkeypatch
    ):
        # LAST in the class: this damages cut's snapshots on disk.
        # A corrupt newest snapshot falls back loudly to the previous
        # retained one; only when EVERY snapshot is unloadable does the
        # resume refuse.
        import testground_tpu.sim.checkpoint as ckpt_mod

        monkeypatch.setattr(ckpt_mod, "_RETRY_BASE_SECS", 0.001)
        monkeypatch.setattr(ckpt_mod, "_RETRY_JITTER_SECS", 0.0)
        env = resumed_runs["env"]
        ckpt_dir = os.path.join(
            env.dirs.outputs(), "network", "cut", CHECKPOINT_DIR
        )
        names = sorted(os.listdir(ckpt_dir))
        assert len(names) >= 2  # keep=2: a fallback candidate exists
        newest = os.path.join(ckpt_dir, names[-1])
        with open(newest, "r+b") as f:
            f.truncate(os.path.getsize(newest) // 3)
        out = _exec("res-fb", env=env, max_ticks=512, resume_from="cut")
        ck = out.result.journal["sim"]["checkpoint"]
        assert ck["resumed"]["from_run"] == "cut"
        fb = ck["resumed"]["fallback"]
        assert fb["skipped"] == [names[-1]] and fb["error"]
        # fell back to the older snapshot, then re-simulated to the end
        newest_tick = int(names[-1][len("ckpt-") : -len(".npz")])
        assert ck["resumed"]["from_tick"] < newest_tick
        # the fallback resume still lands on the uninterrupted endpoint
        full_ticks = resumed_runs["full"].result.journal["sim"]["ticks"]
        assert out.result.journal["sim"]["ticks"] == full_ticks
        # now every retained snapshot is unloadable: refuse loudly
        for name in os.listdir(ckpt_dir):
            path = os.path.join(ckpt_dir, name)
            with open(path, "r+b") as f:
                f.truncate(os.path.getsize(path) // 3)
        with pytest.raises(CheckpointError, match="refusing to resume"):
            _exec("res-bad", env=env, max_ticks=512, resume_from="cut")


# -------------------------------------------------- SLO state continuation


class TestSloStateRoundTrip:
    def test_evaluator_state_roundtrips_exactly(self):
        from testground_tpu.sim.slo import SloEvaluator, build_slo_plan

        groups = make_groups(4)
        plan = build_slo_plan(
            groups,
            {
                "": [
                    {
                        "name": "rate",
                        "metric": "delivered_per_tick",
                        "op": ">=",
                        "threshold": 1e9,  # breaches every chunk
                        "window_ticks": 32,
                    }
                ]
            },
        )
        ev = SloEvaluator(plan, groups, 1.0, 16)
        for tick0 in (0, 16, 32):
            ev.on_rows(
                [
                    {"tick": tick0 + i, "delivered": 3, "sent": 4}
                    for i in range(16)
                ]
            )
            ev.evaluate()
        state = ev.state_dict()
        assert json.loads(json.dumps(state)) == state  # JSON-able

        ev2 = SloEvaluator(plan, groups, 1.0, 16)
        ev2.load_state(state)
        assert ev2.journal() == ev.journal()
        # continued evaluation agrees with an uninterrupted evaluator
        for e in (ev, ev2):
            e.on_rows(
                [{"tick": 48 + i, "delivered": 3, "sent": 4} for i in range(16)]
            )
            e.evaluate()
        assert ev2.journal() == ev.journal()


# ------------------------------------------------------------- CLI resume


class TestCliResume:
    def test_run_resume_continues_a_checkpointed_task(
        self, tg_home, capsys
    ):
        from testground_tpu.cli.main import main

        assert (
            main(
                [
                    "plan",
                    "import",
                    "--from",
                    os.path.join(PLANS, "network"),
                ]
            )
            == 0
        )
        # interrupted-by-budget run: completes FAILURE (incomplete
        # instances) but leaves snapshots at every chunk boundary
        rc = main(
            [
                "run",
                "single",
                "network:ping-pong",
                "-i",
                "4",
                "--run-cfg",
                "checkpoint_chunks=1",
                "--run-cfg",
                "chunk=16",
                "--run-cfg",
                "max_ticks=48",
                "--run-cfg",
                "telemetry=true",
            ]
        )
        out = capsys.readouterr().out
        assert "run is queued with ID:" in out
        task_id = out.split("run is queued with ID:")[1].split()[0].strip()
        assert rc == 1  # incomplete instances → FAILURE, by design

        # resume it to completion through the real CLI verb, extending
        # the budget past the interruption point
        assert (
            main(
                ["run", "resume", task_id, "--run-cfg", "max_ticks=512"]
            )
            == 0
        )
        out2 = capsys.readouterr().out
        assert f"resuming task {task_id}" in out2
        assert "(outcome: success)" in out2

    def test_multi_runs_composition_refuses_readably(
        self, monkeypatch, capsys
    ):
        """One resume_from cannot serve a multi-[[runs]] task (each run
        has its own outputs dir) — the CLI refuses with the per-run
        recipe instead of letting every run fail inside the executor."""
        import time as _time

        from testground_tpu.api import (
            Composition,
            Global,
            Group,
            Instances,
            generate_default_run,
        )
        from testground_tpu.cli import commands
        from testground_tpu.cli.main import main
        from testground_tpu.engine.task import (
            DatedState,
            State,
            Task,
            TaskType,
        )

        comp = generate_default_run(
            Composition(
                global_=Global(
                    plan="network",
                    case="ping-pong",
                    builder="sim:plan",
                    runner="sim:jax",
                ),
                groups=[Group(id="all", instances=Instances(count=2))],
            )
        )
        d = comp.to_dict()
        d["runs"] = d["runs"] + [
            {**d["runs"][0], "id": "second"}
        ]  # two [[runs]]
        tsk = Task(
            id="multi1",
            type=TaskType.RUN,
            plan="network",
            case="ping-pong",
            states=[
                DatedState(state=State.COMPLETE, created=_time.time())
            ],
            composition=d,
        )

        class _Stub:
            def get_task(self, tid):
                return tsk if tid == "multi1" else None

            def stop(self):
                pass

        monkeypatch.setattr(commands, "_engine", lambda args: _Stub())
        assert main(["run", "resume", "multi1"]) == 1
        err = capsys.readouterr().err
        assert "multi-[[runs]]" in err
        assert "--run-ids" in err and "multi1-" in err
