"""Cohort guardrails, enforced without any cohort process (unit tier —
unlike test_multihost.py these never need a working multi-process jax
backend):

- fatal classification is exception-TYPE-first (VERDICT r5 weak #3): a
  plan-authored error mentioning "barrier" can never kill the cohort
  generation;
- the 64 KiB job-spec broadcast bound is prechecked in the engine
  process BEFORE any cohort spawns (VERDICT r5 weak #5).
"""

import pytest


class TestCohortFatalClassification:
    """VERDICT r5 weak #3: fatal = runtime-layer exception TYPE first,
    marker text second. Plan/framework Python errors can never kill the
    cohort generation, whatever their message says."""

    def test_plan_valueerror_mentioning_barrier_is_not_fatal(self):
        from testground_tpu.sim.cohort import _is_cohort_fatal

        # plans use barriers — their errors talk about them
        exc = ValueError("plan failed: barrier 'go' timed out at t=32")
        assert not _is_cohort_fatal(exc)
        assert not _is_cohort_fatal(
            RuntimeError("sync service unavailable for group 'all'")
        )

    def test_xla_runtime_error_with_marker_is_fatal(self):
        from jaxlib.xla_client import XlaRuntimeError

        from testground_tpu.sim.cohort import _is_cohort_fatal

        assert _is_cohort_fatal(
            XlaRuntimeError("DEADLINE_EXCEEDED: barrier timed out")
        )
        assert _is_cohort_fatal(
            XlaRuntimeError("UNAVAILABLE: connection reset by peer")
        )

    def test_runtime_error_without_marker_is_not_fatal(self):
        from jaxlib.xla_client import XlaRuntimeError

        from testground_tpu.sim.cohort import _is_cohort_fatal

        # a runtime-layer error that does NOT indicate a poisoned
        # generation (e.g. an OOM) stays an ordinary run failure
        assert not _is_cohort_fatal(
            XlaRuntimeError("RESOURCE_EXHAUSTED: out of memory")
        )

    def test_distributed_runtime_type_name_matches(self):
        from testground_tpu.sim.cohort import _is_cohort_fatal

        # jax's distributed-runtime errors are matched by TYPE NAME too
        # (their module moved across jax versions)
        DistributedRuntimeError = type(
            "DistributedRuntimeError", (RuntimeError,), {}
        )
        assert _is_cohort_fatal(
            DistributedRuntimeError("coordination service heartbeat lost")
        )


class TestCohortSpecSizePrecheck:
    """VERDICT r5 weak #5: an over-the-wire-bound job spec is refused in
    the ENGINE process, before any cohort process spawns or collective
    is entered — the MAX_FILTER_CELLS precheck philosophy."""

    def _job(self, params):
        from testground_tpu.api import RunGroup, RunInput

        return RunInput(
            run_id="specsize",
            test_plan="network",
            test_case="ping-pong",
            total_instances=4,
            groups=[
                RunGroup(id="all", instances=4, parameters=params)
            ],
        )

    def test_oversized_spec_fails_fast_and_readably(self):
        import threading
        import time as _time

        from testground_tpu.rpc import discard_writer
        from testground_tpu.sim.executor import (
            SimJaxConfig,
            execute_sim_run,
        )

        big = {"blob": "x" * (70 * 1024)}
        job = self._job(big)
        job.runner_config = SimJaxConfig(
            coordinator_address="127.0.0.1:1"
        )
        t0 = _time.monotonic()
        with pytest.raises(ValueError) as ei:
            execute_sim_run(job, discard_writer(), threading.Event())
        # readable: names the bound, the offender, and the refusal point
        msg = str(ei.value)
        assert "65,536" in msg  # bound named
        assert "group 'all'" in msg  # offender named
        assert "before spawning" in msg
        # fast: refused without touching the (dead) coordinator address
        assert _time.monotonic() - t0 < 5.0

    def test_in_bound_spec_passes_the_precheck(self):
        from testground_tpu.sim.executor import (
            SimJaxConfig,
            _precheck_cohort_spec_size,
        )

        cfg = SimJaxConfig(coordinator_address="127.0.0.1:1")
        # a normal composition sails through (no exception)
        _precheck_cohort_spec_size(self._job({"latency_ms": "4"}), cfg)


class TestSimWorkerDeadLeaderExit:
    """VERDICT r5 weak #4: a dead leader must end a `tg sim-worker`
    with ONE readable line and an immediate clean exit — beating the
    distributed runtime's LOG(FATAL) poll — instead of a C++ stack.
    The wrapper classifies with the cohort child's typed-first rule, so
    plan/framework errors still surface as ordinary tracebacks."""

    def _invoke(self, monkeypatch, exc):
        import testground_tpu.sim.executor as executor

        def boom(*a, **kw):
            raise exc

        monkeypatch.setattr(executor, "sim_worker_loop", boom)
        lines = []
        exits = []
        rc = executor.run_sim_worker(
            "127.0.0.1:1",
            2,
            1,
            "/nonexistent-plans",
            log=lines.append,
            _exit=exits.append,
        )
        return rc, lines, exits

    def test_dead_leader_is_one_clean_line(self, monkeypatch):
        from jaxlib.xla_client import XlaRuntimeError

        rc, lines, exits = self._invoke(
            monkeypatch,
            XlaRuntimeError(
                "UNAVAILABLE: coordination service heartbeat failed — "
                "connection closed"
            ),
        )
        # immediate exit requested (os._exit in production), one line
        assert exits == [1] and rc == 1
        assert len(lines) == 1
        line = lines[0]
        assert line.startswith("sim-worker: cohort lost")
        assert "exiting cleanly" in line and "restart" in line

    def test_plan_error_still_raises_normally(self, monkeypatch):
        with pytest.raises(ValueError, match="barrier"):
            self._invoke(
                monkeypatch,
                ValueError("plan failed: barrier 'go' timed out"),
            )

    def test_keyboard_interrupt_passes_through(self, monkeypatch):
        with pytest.raises(KeyboardInterrupt):
            self._invoke(monkeypatch, KeyboardInterrupt())

    def test_healthy_loop_returns_zero(self, monkeypatch):
        import testground_tpu.sim.executor as executor

        monkeypatch.setattr(
            executor, "sim_worker_loop", lambda *a, **kw: None
        )
        assert (
            executor.run_sim_worker(
                "127.0.0.1:1", 2, 1, "/plans", log=lambda s: None
            )
            == 0
        )
