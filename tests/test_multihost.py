"""Multi-host execution test: a REAL two-process jax.distributed cohort on
CPU (the DCN analog — SURVEY.md §2.6/§7-M5). The leader executes a sim:jax
run with coordinator_address set; a follower subprocess runs the
``tg sim-worker`` loop. Both compile the same program over the 4-device
global mesh (2 processes × 2 forced host devices) and the leader's result
must equal a plain single-process run."""

import json
import os
import socket
import subprocess
import sys
import time


REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")

LEADER_SCRIPT = r"""
import json, os, sys, threading
import numpy as np
from testground_tpu.api import RunGroup, RunInput
from testground_tpu.config import EnvConfig
from testground_tpu.rpc import discard_writer
from testground_tpu.sim.executor import SimJaxConfig, execute_sim_run

coord, home = sys.argv[1], sys.argv[2]
n_procs = int(sys.argv[4]) if len(sys.argv) > 4 else 2
env = EnvConfig.load(home)
job = RunInput(
    run_id="mhrun", test_plan="placebo", test_case="ok", total_instances=8,
    groups=[RunGroup(id="all", instances=8,
                     artifact_path=os.path.join(sys.argv[3], "placebo"),
                     parameters={})],
    runner_config=SimJaxConfig(
        chunk=8, coordinator_address=coord, num_processes=n_procs,
        process_id=0,
    ),
    env=env,
)
try:
    out = execute_sim_run(job, discard_writer(), threading.Event())
except RuntimeError as e:
    print(json.dumps({"aborted": str(e)}), flush=True)
else:
    import jax
    print(json.dumps({
        "outcome": out.result.outcome.value,
        "outcomes": {k: {"ok": v.ok, "total": v.total}
                      for k, v in out.result.outcomes.items()},
        "processes": jax.process_count(),
        "devices": len(jax.devices()),
    }), flush=True)
# the coordinator (process 0) must outlive the follower's distributed
# shutdown — hold until the test signals via stdin
sys.stdin.readline()
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _read_json_line(stream, timeout: float) -> str:
    """Next stdout line that looks like JSON (gloo chatter also lands on
    stdout), within ``timeout``."""
    import select

    deadline = time.time() + timeout
    while time.time() < deadline:
        r, _, _ = select.select([stream], [], [], 1.0)
        if r:
            line = stream.readline()
            if line.strip().startswith("{"):
                return line
    raise TimeoutError("no result line from the leader")


def _run_cohort(tmp_path, follower_plans, n_procs=2):
    """Launch leader + (n_procs-1) follower subprocesses, honoring the
    cohort's shutdown-barrier sequencing; returns
    (leader_result, combined_follower_output)."""
    port = _free_port()
    coord = f"127.0.0.1:{port}"

    def env_for():
        # a CLEAN environment, not an inherited one: accelerator-tunnel /
        # relay variables from the host session (sitecustomize backends,
        # remote-compile relays) leak into the cohort and hang the
        # distributed handshake of the CPU children
        return {
            "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "HOME": os.environ.get("HOME", "/root"),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "TESTGROUND_HOME": str(tmp_path / "home"),
            "PYTHONPATH": REPO_ROOT,
        }

    leader = subprocess.Popen(
        [sys.executable, "-c", LEADER_SCRIPT, coord, str(tmp_path / "home"),
         PLANS, str(n_procs)],
        env=env_for(),
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    # wait for the coordinator service to be listening before the follower
    # dials it (jax.distributed's client retry window is finicky when the
    # connect races the very first bind)
    deadline = time.time() + 120
    while time.time() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                break
        except OSError:
            if leader.poll() is not None:
                out, err = leader.communicate()
                raise AssertionError(f"leader died early:\n{err[-2000:]}")
            time.sleep(0.5)
    followers = [
        subprocess.Popen(
            [
                sys.executable,
                "-m",
                "testground_tpu.cli.main",
                "sim-worker",
                "--coordinator",
                coord,
                "--num-processes",
                str(n_procs),
                "--process-id",
                str(pid),
                "--plans",
                follower_plans,
                "--once",
            ],
            env=env_for(),
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in range(1, n_procs)
    ]
    try:
        # jax.distributed.shutdown is a BARRIER: every process must reach
        # it or none exits. Wait for the leader's result line (its work is
        # done, cohort still open), then release it via stdin - its exit
        # completes the follower's shutdown barrier too.
        result_line = _read_json_line(leader.stdout, 240)
        leader.stdin.write("\n")
        leader.stdin.flush()
        lout, lerr = leader.communicate(timeout=120)
        fouts = []
        for follower in followers:
            fout, ferr = follower.communicate(timeout=120)
            fouts.append(fout + ferr)
    except (subprocess.TimeoutExpired, TimeoutError) as e:
        leader.kill()
        for follower in followers:
            follower.kill()
        lout, lerr = leader.communicate()
        ferrs = "".join(
            "".join(follower.communicate()) for follower in followers
        )
        raise AssertionError(
            f"cohort timed out ({e}).\nLEADER err:\n{lerr[-2000:]}\n"
            f"FOLLOWERS:\n{ferrs[-2000:]}"
        )
    assert leader.returncode == 0, f"leader failed:\n{lerr[-3000:]}"
    for i, follower in enumerate(followers):
        assert follower.returncode == 0, (
            f"follower {i + 1} failed:\n{fouts[i][-3000:]}"
        )
    return json.loads(result_line), "".join(fouts)


def test_two_process_cohort_runs_to_completion(tmp_path):
    """Leader (engine) + follower (tg sim-worker --once) over a local
    coordinator; 4 global devices; outcome must be all-success."""
    result, fol = _run_cohort(tmp_path, PLANS)
    assert result["processes"] == 2
    assert result["devices"] == 4
    assert result["outcome"] == "success"
    assert result["outcomes"]["all"] == {"ok": 8, "total": 8}
    assert "sim-worker: run mhrun done" in fol


def test_unsatisfiable_job_is_skipped_in_lockstep(tmp_path):
    """A worker whose plans dir lacks the plan votes not-ready; the whole
    cohort skips the job BEFORE any program collective - the leader gets
    a clean error instead of a hang, the worker exits cleanly."""
    empty = tmp_path / "empty-plans"
    empty.mkdir()
    result, fol = _run_cohort(tmp_path, str(empty))
    assert "aborted" in result, result
    assert "cohort member cannot satisfy" in result["aborted"]
    assert "cohort skipped run mhrun" in fol


def test_three_process_cohort_runs_to_completion(tmp_path):
    """Leader + TWO followers (6 global devices): the fan-out path, not
    just a pair — every process compiles the same program and the
    instance axis shards over the union of the hosts' devices."""
    result, fol = _run_cohort(tmp_path, PLANS, n_procs=3)
    assert result["processes"] == 3
    assert result["devices"] == 6
    assert result["outcome"] == "success"
    assert result["outcomes"]["all"] == {"ok": 8, "total": 8}
    assert fol.count("sim-worker: run mhrun done") == 2
