"""Multi-host execution test: a REAL two-process jax.distributed cohort on
CPU (the DCN analog — SURVEY.md §2.6/§7-M5). The leader executes a sim:jax
run with coordinator_address set; a follower subprocess runs the
``tg sim-worker`` loop. Both compile the same program over the 4-device
global mesh (2 processes × 2 forced host devices) and the leader's result
must equal a plain single-process run."""

import json
import os
import socket
import subprocess
import sys
import time

import pytest


REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")

LEADER_SCRIPT = r"""
import json, os, sys, threading
import numpy as np
from testground_tpu.api import RunGroup, RunInput
from testground_tpu.config import EnvConfig
from testground_tpu.rpc import discard_writer
from testground_tpu.sim.executor import SimJaxConfig, execute_sim_run

coord, home = sys.argv[1], sys.argv[2]
n_procs = int(sys.argv[4]) if len(sys.argv) > 4 else 2
spec = json.loads(sys.argv[5]) if len(sys.argv) > 5 else {}
plan = spec.get("plan", "placebo")
case = spec.get("case", "ok")
instances = int(spec.get("instances", 8))
env = EnvConfig.load(home)
cfg = SimJaxConfig(
    chunk=int(spec.get("chunk", 8)),
    validate=bool(spec.get("validate", False)),
)
if coord:  # multi-host cohort leader; empty coord = plain single process
    cfg.coordinator_address = coord
    cfg.num_processes = n_procs
    cfg.process_id = 0
job = RunInput(
    run_id=spec.get("run_id", "mhrun"), test_plan=plan, test_case=case,
    total_instances=instances,
    groups=[RunGroup(id="all", instances=instances,
                     artifact_path=os.path.join(sys.argv[3], plan),
                     parameters=dict(spec.get("params", {})))],
    runner_config=cfg,
    env=env,
)
try:
    out = execute_sim_run(job, discard_writer(), threading.Event())
except RuntimeError as e:
    print(json.dumps({"aborted": str(e)}), flush=True)
else:
    # process/device counts come from the run's journal: the engine
    # process no longer joins the cohort itself (the isolated leader
    # child does — sim/cohort.py), so local jax state says nothing
    # about the cohort
    sim = out.result.journal.get("sim", {})
    print(json.dumps({
        "outcome": out.result.outcome.value,
        "outcomes": {k: {"ok": v.ok, "total": v.total}
                      for k, v in out.result.outcomes.items()},
        "metrics": out.result.journal.get("metrics", {}),
        "processes": sim.get("processes", 1),
        "devices": sim.get("devices", 1),
    }), flush=True)
# the coordinator (process 0) must outlive the follower's distributed
# shutdown — hold until the test signals via stdin
sys.stdin.readline()
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _read_json_line(stream, timeout: float) -> str:
    """Next stdout line that looks like JSON (gloo chatter also lands on
    stdout), within ``timeout``."""
    import select

    deadline = time.time() + timeout
    while time.time() < deadline:
        r, _, _ = select.select([stream], [], [], 1.0)
        if r:
            line = stream.readline()
            if line == "":  # EOF: the leader died — fail now with its
                # stderr, not after busy-spinning out the whole timeout
                raise TimeoutError("leader exited without a result line")
            if line.strip().startswith("{"):
                return line
    raise TimeoutError("no result line from the leader")


def _clean_env(home, device_count=2):
    # a CLEAN environment, not an inherited one: accelerator-tunnel /
    # relay variables from the host session (sitecustomize backends,
    # remote-compile relays) leak into the cohort and hang the
    # distributed handshake of the CPU children
    return {
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        "HOME": os.environ.get("HOME", "/root"),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={device_count}",
        "TESTGROUND_HOME": str(home),
        "PYTHONPATH": REPO_ROOT,
    }


_COHORT_CAPABILITY: dict = {}

_PROBE_SCRIPT = """
import sys
import jax
jax.distributed.initialize(sys.argv[1], 2, int(sys.argv[2]))
import jax.numpy as jnp
from jax.experimental import multihost_utils
multihost_utils.broadcast_one_to_all(jnp.zeros((1,), jnp.int32))
print("COHORT_PROBE_OK", flush=True)
"""


def _cohort_backend_supported() -> tuple:
    """One-shot capability probe: can THIS jax build actually execute a
    multi-process collective on the CPU backend? Some wheels join the
    cohort fine and then refuse the first collective ("Multiprocess
    computations aren't implemented on the CPU backend") — every test
    in this module would fail on that environment, each burning ~30 s of
    subprocess turnaround, so probe once with the smallest possible
    cohort (2 processes, one broadcast) and skip the module with the
    backend's own words instead."""
    if _COHORT_CAPABILITY:
        return _COHORT_CAPABILITY["ok"], _COHORT_CAPABILITY["why"]
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE_SCRIPT,
             f"127.0.0.1:{port}", str(pid)],
            env=_clean_env("/tmp/tg-cohort-probe", device_count=1),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    ok = True
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            ok = False
        outs.append(out or "")
        ok = ok and p.returncode == 0 and "COHORT_PROBE_OK" in (out or "")
    why = ""
    if not ok:
        blob = "\n".join(outs)
        marker = "Multiprocess computations aren't implemented"
        if marker in blob:
            why = f"{marker} on this backend"
        else:
            lines = [ln for ln in blob.strip().splitlines() if ln.strip()]
            why = (lines[-1][:200] if lines else "probe produced no output")
    _COHORT_CAPABILITY.update(ok=ok, why=why)
    return ok, why


@pytest.fixture(autouse=True, scope="module")
def _require_cohort_backend():
    ok, why = _cohort_backend_supported()
    if not ok:
        pytest.skip(
            "jax cannot execute multi-process cohorts in this "
            f"environment: {why}"
        )


def _run_single(tmp_path, spec, home_name="home-single"):
    """The ground-truth run: same LEADER_SCRIPT, no coordinator, ONE
    device (which also makes it the flat-calendar layout — the cohort's
    sharded 2-D layout must still match it bit for bit)."""
    home = tmp_path / home_name
    proc = subprocess.Popen(
        [sys.executable, "-c", LEADER_SCRIPT, "", str(home), PLANS, "1",
         json.dumps(spec)],
        env=_clean_env(home, device_count=1),
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    try:
        line = _read_json_line(proc.stdout, 300)
        proc.stdin.write("\n")
        proc.stdin.flush()
        out, err = proc.communicate(timeout=60)
    except (subprocess.TimeoutExpired, TimeoutError) as e:
        proc.kill()
        out, err = proc.communicate()
        raise AssertionError(f"single run timed out ({e}):\n{err[-2000:]}")
    assert proc.returncode == 0, f"single run failed:\n{err[-3000:]}"
    return json.loads(line), str(home)


def _run_cohort(tmp_path, follower_plans, n_procs=2, spec=None):
    """Launch leader + (n_procs-1) follower subprocesses, honoring the
    cohort's shutdown-barrier sequencing; returns
    (leader_result, combined_follower_output)."""
    port = _free_port()
    coord = f"127.0.0.1:{port}"

    def env_for():
        return _clean_env(tmp_path / "home")

    leader = subprocess.Popen(
        [sys.executable, "-c", LEADER_SCRIPT, coord, str(tmp_path / "home"),
         PLANS, str(n_procs), json.dumps(spec or {})],
        env=env_for(),
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    # wait for the coordinator service to be listening before the follower
    # dials it (jax.distributed's client retry window is finicky when the
    # connect races the very first bind)
    deadline = time.time() + 120
    while time.time() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                break
        except OSError:
            if leader.poll() is not None:
                out, err = leader.communicate()
                raise AssertionError(f"leader died early:\n{err[-2000:]}")
            time.sleep(0.5)
    followers = [
        subprocess.Popen(
            [
                sys.executable,
                "-m",
                "testground_tpu.cli.main",
                "sim-worker",
                "--coordinator",
                coord,
                "--num-processes",
                str(n_procs),
                "--process-id",
                str(pid),
                "--plans",
                follower_plans,
                "--once",
            ],
            env=env_for(),
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in range(1, n_procs)
    ]
    try:
        # jax.distributed.shutdown is a BARRIER: every process must reach
        # it or none exits. Wait for the leader's result line (its work is
        # done, cohort still open), then release it via stdin - its exit
        # completes the follower's shutdown barrier too.
        result_line = _read_json_line(leader.stdout, 240)
        leader.stdin.write("\n")
        leader.stdin.flush()
        lout, lerr = leader.communicate(timeout=120)
        fouts = []
        for follower in followers:
            fout, ferr = follower.communicate(timeout=120)
            fouts.append(fout + ferr)
    except (subprocess.TimeoutExpired, TimeoutError) as e:
        leader.kill()
        for follower in followers:
            follower.kill()
        lout, lerr = leader.communicate()
        ferrs = "".join(
            "".join(follower.communicate()) for follower in followers
        )
        raise AssertionError(
            f"cohort timed out ({e}).\nLEADER err:\n{lerr[-2000:]}\n"
            f"FOLLOWERS:\n{ferrs[-2000:]}"
        )
    assert leader.returncode == 0, f"leader failed:\n{lerr[-3000:]}"
    for i, follower in enumerate(followers):
        assert follower.returncode == 0, (
            f"follower {i + 1} failed:\n{fouts[i][-3000:]}"
        )
    return json.loads(result_line), "".join(fouts)


def test_two_process_cohort_runs_to_completion(tmp_path):
    """Leader (engine) + follower (tg sim-worker --once) over a local
    coordinator; 4 global devices; outcome must be all-success."""
    result, fol = _run_cohort(tmp_path, PLANS)
    assert result["processes"] == 2
    assert result["devices"] == 4
    assert result["outcome"] == "success"
    assert result["outcomes"]["all"] == {"ok": 8, "total": 8}
    assert "sim-worker: run mhrun done" in fol


def test_unsatisfiable_job_is_skipped_in_lockstep(tmp_path):
    """A worker whose plans dir lacks the plan votes not-ready; the whole
    cohort skips the job BEFORE any program collective - the leader gets
    a clean error instead of a hang, the worker exits cleanly."""
    empty = tmp_path / "empty-plans"
    empty.mkdir()
    result, fol = _run_cohort(tmp_path, str(empty))
    assert "aborted" in result, result
    assert "cohort member cannot satisfy" in result["aborted"]
    assert "cohort skipped run mhrun" in fol


def test_three_process_cohort_runs_to_completion(tmp_path):
    """Leader + TWO followers (6 global devices): the fan-out path, not
    just a pair — every process compiles the same program and the
    instance axis shards over the union of the hosts' devices."""
    result, fol = _run_cohort(tmp_path, PLANS, n_procs=3)
    assert result["processes"] == 3
    assert result["devices"] == 6
    assert result["outcome"] == "success"
    assert result["outcomes"]["all"] == {"ok": 8, "total": 8}
    assert fol.count("sim-worker: run mhrun done") == 2


# --------------------------------------------------------------------------
# Message-bearing workloads across the process boundary (VERDICT r3 #1):
# the cluster analog must carry real traffic between processes, like the
# reference's k8s pods do (cluster_k8s.go:300-305,696), and the sharded
# cohort result must be bit-equal to a single-process, single-device run.


def _instance_digest(home, plan, run_id="mhrun"):
    """Per-instance (status, finished_at, metrics) read from the outputs
    layout — the cross-run equality surface."""
    root = os.path.join(home, "data", "outputs", plan, run_id)
    digest = {}
    for group in sorted(os.listdir(root)):
        gdir = os.path.join(root, group)
        if not os.path.isdir(gdir):
            continue
        for inst in sorted(os.listdir(gdir), key=int):
            d = os.path.join(gdir, inst)
            with open(os.path.join(d, "run.out")) as f:
                evt = json.loads(f.readline())
            entry = {
                "status": evt["event"]["type"],
                "finished_at": evt["finished_at_tick"],
            }
            mpath = os.path.join(d, "metrics.out")
            if os.path.isfile(mpath):
                with open(mpath) as f:
                    entry["metrics"] = {
                        row["name"]: row["value"]
                        for row in map(json.loads, f)
                    }
            digest[(group, int(inst))] = entry
    return digest


class TestMessageBearingCohorts:
    def _assert_cohort_equals_single(
        self, tmp_path, plan, case, instances, params, n_procs,
        validate=False,
    ):
        run_id = f"mh-{case}"  # unique per call: homes are shared
        spec = {
            "plan": plan,
            "case": case,
            "instances": instances,
            "params": params,
            "chunk": 64,
            "run_id": run_id,
            "validate": validate,
        }
        result, _ = _run_cohort(tmp_path, PLANS, n_procs=n_procs, spec=spec)
        assert result["outcome"] == "success", result
        assert result["outcomes"]["all"]["ok"] == instances
        single, single_home = _run_single(tmp_path, spec)
        assert single["outcome"] == "success", single

        # journal metric aggregates AND every per-instance record
        # (status, finish tick, exact metric floats) must match
        assert result["metrics"] == single["metrics"]
        cohort_digest = _instance_digest(
            str(tmp_path / "home"), plan, run_id
        )
        single_digest = _instance_digest(single_home, plan, run_id)
        assert cohort_digest == single_digest
        assert len(cohort_digest) == instances
        return cohort_digest

    def test_pingpong_two_process_bit_equal(self, tmp_path):
        """network/ping-pong (RTT windows + mid-run reshape) through a
        REAL 2-process cohort: message traffic crosses the jax.distributed
        process boundary and the result is bit-equal to the 1-device
        single-process run (reference traffic parity:
        plans/network/pingpong.go:54)."""
        digest = self._assert_cohort_equals_single(
            tmp_path,
            "network",
            "ping-pong",
            instances=8,
            params={
                "latency_ms": "100",
                "latency2_ms": "10",
                "tolerance_ms": "15",
            },
            n_procs=2,
        )
        # the workload really measured traffic: every instance carries a
        # nonzero RTT metric
        for entry in digest.values():
            assert any("rtt" in k for k in entry.get("metrics", {})), entry

    def test_splitbrain_reject_three_process_bit_equal(self, tmp_path):
        """splitbrain/reject through a 3-process cohort: the mod-3 region
        partition interleaves across contiguous shards, so probe traffic
        and REJECT feedback cross every process boundary — the declared
        #1 scaling risk (cross-process calendar scatter), now executed
        with real messages."""
        digest = self._assert_cohort_equals_single(
            tmp_path,
            "splitbrain",
            "reject",
            instances=9,
            params={},
            n_procs=3,
        )
        # region-A instances saw rejections (the PROHIBIT feedback made
        # the crossing too)
        rejected = [
            entry["metrics"].get("splitbrain.rejected", 0)
            for entry in digest.values()
        ]
        assert any(v > 0 for v in rejected), rejected

    def test_splitbrain_accept_and_drop_two_process(self, tmp_path):
        """The remaining filter actions through a 2-process cohort."""
        for case in ("accept", "drop"):
            self._assert_cohort_equals_single(
                tmp_path,
                "splitbrain",
                case,
                instances=6,
                params={},
                n_procs=2,
            )

    def test_splitbrain_drop_four_process_bit_equal(self, tmp_path):
        """The widest fan-out: leader + THREE followers (8 global
        devices) running splitbrain/drop at 12 instances — the mod-3
        regions interleave across four processes' shards and the result
        still matches single-process bit for bit."""
        self._assert_cohort_equals_single(
            tmp_path,
            "splitbrain",
            "drop",
            instances=12,
            params={},
            n_procs=4,
        )

    def test_direct_mode_validate_in_cohort(self, tmp_path):
        """A direct-slot-mode plan under validate=true through a real
        cohort: the leader broadcasts the flag, so BOTH processes trace
        the validate-enabled program (a mismatch would trace different
        programs and desync inside a collective). The clean flood passes
        the collision check and stays bit-equal to single-process."""
        self._assert_cohort_equals_single(
            tmp_path,
            "benchmarks",
            "pingpong-flood",
            instances=8,
            params={"duration_ticks": "64", "latency_ms": "4"},
            n_procs=2,
            validate=True,
        )

    def test_traffic_shaped_two_process_bit_equal(self, tmp_path):
        """The HTB bandwidth queue (r4's new shaping mode) through a
        real cohort: the per-src backlog state is instance-sharded, so
        the token bucket must meter identically when its halves live on
        different processes — the plan's exact-pacing assertions plus
        per-instance bit-equality gate it."""
        self._assert_cohort_equals_single(
            tmp_path,
            "network",
            "traffic-shaped",
            instances=8,
            params={"burst": "6", "rate": "1.5"},
            n_procs=2,
        )

    def test_storm_two_process_bit_equal(self, tmp_path):
        """storm's random 5-out gossip graph is the WORST-case
        cross-shard scatter (every instance floods arbitrary peers) —
        through a real 2-process cohort it exercises cross-process
        calendar traffic far beyond the pairwise workloads, and the
        byte counters must still match single-process exactly
        (reference: plans/benchmarks/storm.go:66-120)."""
        digest = self._assert_cohort_equals_single(
            tmp_path,
            "benchmarks",
            "storm",
            instances=16,
            params={
                "conn_outgoing": "5",
                "conn_delay_ticks": "8",
                "data_size_kb": "64",
            },
            n_procs=2,
        )
        sent = sum(
            e["metrics"].get("storm.bytes_sent", 0) for e in digest.values()
        )
        assert sent > 0, digest


# --------------------------------------------------------------------------
# Mid-run cohort member death (VERDICT r4 #2): the watchRunPods analog
# (cluster_k8s.go:696) — a SIGKILLed member must fail the leader's TASK
# with a readable error in bounded time, and the engine process must
# survive (the distributed runtime would otherwise LOG(FATAL) any process
# that joined the cohort — see sim/cohort.py).

DEATH_LEADER_SCRIPT = r"""
import json, os, sys, threading, time
from testground_tpu.api import RunGroup, RunInput
from testground_tpu.config import EnvConfig
from testground_tpu.rpc import OutputWriter
from testground_tpu.sim.executor import SimJaxConfig, execute_sim_run
coord, home, plans, logpath = sys.argv[1:5]
n_procs = int(sys.argv[5]) if len(sys.argv) > 5 else 2
env = EnvConfig.load(home)
cfg = SimJaxConfig(
    chunk=8, coordinator_address=coord, num_processes=n_procs, process_id=0
)
job = RunInput(
    run_id="deathrun", test_plan="network", test_case="pingpong-sustained",
    total_instances=8,
    groups=[RunGroup(id="all", instances=8,
                     artifact_path=os.path.join(plans, "network"),
                     parameters={"duration_ticks": "1000000",
                                 "latency_ms": "4", "latency2_ms": "2",
                                 "reshape_every": "1000"})],
    runner_config=cfg, env=env)
ow = OutputWriter(sink=open(logpath, "w", buffering=1))
try:
    out = execute_sim_run(job, ow, threading.Event())
    print(json.dumps({"outcome": out.result.outcome.value}), flush=True)
except RuntimeError as e:
    print(json.dumps({"aborted": str(e)}), flush=True)
sys.stdin.readline()
"""


class TestCohortMemberDeath:
    def _run_death(self, tmp_path, n_procs, kill_idx):
        """Form an n_procs cohort, SIGKILL follower `kill_idx` once the
        chunk loop demonstrably runs, and assert the leader's task fails
        readably in bounded time while the engine process survives."""
        port = _free_port()
        coord = f"127.0.0.1:{port}"
        home = tmp_path / "home"
        logpath = str(tmp_path / "leader.log")
        leader = subprocess.Popen(
            [sys.executable, "-c", DEATH_LEADER_SCRIPT, coord, str(home),
             PLANS, logpath, str(n_procs)],
            env=_clean_env(home),
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        followers = []
        try:
            deadline = time.time() + 120
            while time.time() < deadline:
                try:
                    with socket.create_connection(
                        ("127.0.0.1", port), timeout=1
                    ):
                        break
                except OSError:
                    assert leader.poll() is None, "leader died early"
                    time.sleep(0.5)
            # append one-by-one (not a comprehension) so a failed spawn
            # still leaves the earlier followers reachable by the
            # finally-block cleanup
            for pid in range(1, n_procs):
                followers.append(
                    subprocess.Popen(
                        [sys.executable, "-m", "testground_tpu.cli.main",
                         "sim-worker", "--coordinator", coord,
                         "--num-processes", str(n_procs),
                         "--process-id", str(pid),
                         "--plans", PLANS, "--once"],
                        env=_clean_env(home),
                        stdout=subprocess.DEVNULL,
                        stderr=subprocess.DEVNULL,
                    )
                )
            # wait until the chunk loop is demonstrably executing (the
            # 5-second cadence progress line), so the kill lands MID-RUN,
            # not during compile or setup
            deadline = time.time() + 300
            while time.time() < deadline:
                assert leader.poll() is None, (
                    "leader exited before the run started:\n"
                    + leader.stderr.read()[-2000:]
                )
                try:
                    content = open(logpath).read()
                except FileNotFoundError:
                    content = ""
                if "deathrun:" in content and "ticks" in content:
                    break
                time.sleep(0.5)
            else:
                raise AssertionError("run never reached the chunk loop")

            followers[kill_idx].kill()
            t_kill = time.time()
            line = _read_json_line(leader.stdout, 60)
            elapsed = time.time() - t_kill
            res = json.loads(line)
            assert "aborted" in res, res
            assert "cohort member" in res["aborted"].lower(), res
            assert "sim-worker" in res["aborted"], res  # remediation hint
            assert elapsed < 60, f"failure took {elapsed:.1f}s"

            # the engine process survived the member death and exits
            # cleanly — the daemon would keep serving
            leader.stdin.write("\n")
            leader.stdin.flush()
            _, lerr = leader.communicate(timeout=60)
            assert leader.returncode == 0, lerr[-3000:]
        finally:
            for p in [leader] + followers:
                if p is not None and p.poll() is None:
                    p.kill()

    def test_follower_sigkill_fails_task_cleanly_and_engine_survives(
        self, tmp_path
    ):
        self._run_death(tmp_path, n_procs=2, kill_idx=0)

    def test_one_of_two_followers_dying_fails_the_three_process_cohort(
        self, tmp_path
    ):
        """The mechanism is not pair-specific: with two followers, one
        death must fail the run the same way (the survivor's runtime is
        poisoned too — the whole generation restarts, as a lost pod
        fails the reference's whole run)."""
        self._run_death(tmp_path, n_procs=3, kill_idx=1)


CANCEL_LEADER_SCRIPT = r"""
import json, os, sys, threading, time
from testground_tpu.api import RunGroup, RunInput
from testground_tpu.config import EnvConfig
from testground_tpu.rpc import OutputWriter
from testground_tpu.sim.executor import SimJaxConfig, execute_sim_run
coord, home, plans, logpath = sys.argv[1:5]
env = EnvConfig.load(home)
cfg = SimJaxConfig(
    chunk=8, coordinator_address=coord, num_processes=2, process_id=0
)
job = RunInput(
    run_id="cancelrun", test_plan="network", test_case="pingpong-sustained",
    total_instances=8,
    groups=[RunGroup(id="all", instances=8,
                     artifact_path=os.path.join(plans, "network"),
                     parameters={"duration_ticks": "1000000",
                                 "latency_ms": "4", "latency2_ms": "2",
                                 "reshape_every": "1000"})],
    runner_config=cfg, env=env)
ow = OutputWriter(sink=open(logpath, "w", buffering=1))
cancel = threading.Event()

def watch():  # cancel once the chunk loop demonstrably runs
    while not cancel.is_set():
        try:
            if "ticks" in open(logpath).read():
                cancel.set()
                return
        except OSError:
            pass
        time.sleep(0.5)

threading.Thread(target=watch, daemon=True).start()
out = execute_sim_run(job, ow, cancel)
print(json.dumps({"outcome": out.result.outcome.value}), flush=True)
sys.stdin.readline()
"""


class TestCohortCancel:
    def test_cancel_stops_cohort_in_lockstep(self, tmp_path):
        """Engine-side cancellation forwards through the leader child and
        broadcasts to the cohort: the task ends CANCELED, the follower
        survives to serve the shutdown sentinel (nobody strands in a
        collective), and both exit cleanly."""
        port = _free_port()
        coord = f"127.0.0.1:{port}"
        home = tmp_path / "home"
        logpath = str(tmp_path / "leader.log")
        leader = subprocess.Popen(
            [sys.executable, "-c", CANCEL_LEADER_SCRIPT, coord, str(home),
             PLANS, logpath],
            env=_clean_env(home),
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        follower = None
        try:
            deadline = time.time() + 120
            while time.time() < deadline:
                try:
                    with socket.create_connection(
                        ("127.0.0.1", port), timeout=1
                    ):
                        break
                except OSError:
                    assert leader.poll() is None, "leader died early"
                    time.sleep(0.5)
            follower = subprocess.Popen(
                [sys.executable, "-m", "testground_tpu.cli.main",
                 "sim-worker", "--coordinator", coord,
                 "--num-processes", "2", "--process-id", "1",
                 "--plans", PLANS, "--once"],
                env=_clean_env(home),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
            line = _read_json_line(leader.stdout, 300)
            assert json.loads(line)["outcome"] == "canceled"
            leader.stdin.write("\n")
            leader.stdin.flush()
            _, lerr = leader.communicate(timeout=120)
            assert leader.returncode == 0, lerr[-3000:]
            fout, _ = follower.communicate(timeout=120)
            assert follower.returncode == 0, fout[-3000:]
            assert "sim-worker: shutdown" in fout
        finally:
            for p in (leader, follower):
                if p is not None and p.poll() is None:
                    p.kill()


class TestEngineCohort:
    def test_engine_task_runs_cohort_and_stop_drains_it(
        self, tmp_path, monkeypatch
    ):
        """The daemon-shaped path: an in-process Engine executes a
        multi-host run task (runner config carries the coordinator), the
        isolated leader child joins the cohort on the engine's behalf,
        and engine.stop() drains the worker through the child's shutdown
        broadcast — the engine process itself never joins jax.distributed
        (its own jax state stays single-process)."""
        import jax

        from testground_tpu.api import (
            Composition, Global, Group, Instances, TestPlanManifest,
            generate_default_run,
        )
        from testground_tpu.builders.sim_plan import SimPlanBuilder
        from testground_tpu.config import EnvConfig
        from testground_tpu.engine import Engine, EngineConfig, Outcome, State
        from testground_tpu.sim.runner import SimJaxRunner

        home = tmp_path / "home"
        # the leader CHILD inherits this process's env: pin it to the
        # worker's topology (cohorts need UNIFORM per-process device
        # counts — jax.multihost_utils shapes collectives as
        # [n_processes, local_devices]) and scrub the accelerator-tunnel
        # activation vars, which would otherwise hijack the child onto a
        # remote backend that cannot join the CPU cohort (the executor
        # now refuses that loudly rather than running single-process)
        monkeypatch.setenv("TESTGROUND_HOME", str(home))
        monkeypatch.setenv(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=2"
        )
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        for var in (
            "PALLAS_AXON_POOL_IPS",
            "PALLAS_AXON_REMOTE_COMPILE",
            "AXON_LOOPBACK_RELAY",
        ):
            monkeypatch.delenv(var, raising=False)
        port = _free_port()
        engine = Engine(
            EngineConfig(
                env=EnvConfig.load(),
                builders=[SimPlanBuilder()],
                runners=[SimJaxRunner()],
            )
        )
        engine.start_workers()
        follower = None
        try:
            comp = generate_default_run(
                Composition(
                    global_=Global(
                        plan="network", case="ping-pong",
                        builder="sim:plan", runner="sim:jax",
                        run_config={
                            "coordinator_address": f"127.0.0.1:{port}",
                            "num_processes": 2,
                            "process_id": 0,
                            "chunk": 8,
                        },
                    ),
                    groups=[
                        Group(id="all", instances=Instances(count=8))
                    ],
                )
            )
            manifest = TestPlanManifest.load_file(
                os.path.join(PLANS, "network", "manifest.toml")
            )
            tid = engine.queue_run(
                comp, manifest, sources_dir=os.path.join(PLANS, "network")
            )
            deadline = time.time() + 120
            while time.time() < deadline:
                try:
                    with socket.create_connection(
                        ("127.0.0.1", port), timeout=1
                    ):
                        break
                except OSError:
                    time.sleep(0.5)
            follower = subprocess.Popen(
                [sys.executable, "-m", "testground_tpu.cli.main",
                 "sim-worker", "--coordinator", f"127.0.0.1:{port}",
                 "--num-processes", "2", "--process-id", "1",
                 "--plans", PLANS, "--once"],
                env=_clean_env(home),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
            deadline = time.time() + 300
            while time.time() < deadline:
                t = engine.get_task(tid)
                if t is not None and t.state().state in (
                    State.COMPLETE, State.CANCELED,
                ):
                    break
                time.sleep(0.2)
            assert t.outcome() == Outcome.SUCCESS, t.error
            assert t.result["outcomes"]["all"]["ok"] == 8
            # the engine's own jax never joined the cohort
            assert jax.process_count() == 1
            # stop() drains the worker through the leader child
            engine.stop()
            fout, _ = follower.communicate(timeout=120)
            assert follower.returncode == 0, fout[-3000:]
            assert "sim-worker: shutdown" in fout
        finally:
            if follower is not None and follower.poll() is None:
                follower.kill()
            engine.stop()
