"""badplan: deliberately-broken sim testcases for the static-analysis
plane (``tg check --trace-plans``; tests/test_check.py).

Each testcase violates exactly ONE invariant the checker lints for, so
a test can assert the precise rule id that fires — and that the clean
control case fires none.
"""

import jax
import jax.numpy as jnp
from jax import lax

from testground_tpu.sim.api import SUCCESS, SimTestcase


class IntOnCount(SimTestcase):
    """Calls python ``int()`` on ``env.test_instance_count``. Fine at
    exact shapes (the count is a static python int), but under shape
    bucketing the count is a TRACED runtime scalar — the traced-count
    contract violation ``plan.traced-int`` exists to catch."""

    def init(self, env):
        return {"n": jnp.int32(0)}

    def step(self, env, state, inbox, sync, t):
        # the contract violation: python arithmetic on a traced count
        peers = int(env.test_instance_count) - 1
        return self.out(
            {"n": state["n"] + peers},
            status=jnp.where(t >= 2, SUCCESS, 0),
        )


class DebugPrint(SimTestcase):
    """``jax.debug.print`` in the hot path: a host callback compiled
    into every tick (``plan.host-callback``)."""

    def init(self, env):
        return {"n": jnp.int32(0)}

    def step(self, env, state, inbox, sync, t):
        jax.debug.print("tick {t}", t=t)
        return self.out(
            {"n": state["n"] + 1},
            status=jnp.where(t >= 2, SUCCESS, 0),
        )


class WhileTick(SimTestcase):
    """``lax.while_loop`` in step: per-tick work without a static bound
    (``plan.while-loop``)."""

    def init(self, env):
        return {"n": jnp.int32(0)}

    def step(self, env, state, inbox, sync, t):
        n = lax.while_loop(
            lambda c: c < state["n"] + 3, lambda c: c + 1, jnp.int32(0)
        )
        return self.out(
            {"n": n}, status=jnp.where(t >= 2, SUCCESS, 0)
        )


class WeakState(SimTestcase):
    """State leaves built from bare python literals: weak-typed arrays
    whose dtype re-promotes against the first strong operand — a
    retrace/compile-cache hazard (``plan.weak-type``)."""

    def init(self, env):
        return {"x": jnp.asarray(0.0), "k": jnp.asarray(1)}

    def step(self, env, state, inbox, sync, t):
        return self.out(
            {"x": state["x"] + 0.5, "k": state["k"]},
            status=jnp.where(t >= 2, SUCCESS, 0),
        )


class Clean(SimTestcase):
    """The control: explicit dtypes, no callbacks, no loops — zero
    findings expected."""

    def init(self, env):
        return {"n": jnp.zeros((), jnp.int32)}

    def step(self, env, state, inbox, sync, t):
        n = state["n"] + jnp.int32(1)
        return self.out(
            {"n": n}, status=jnp.where(t >= 2, SUCCESS, 0)
        )


sim_testcases = {
    "int-on-count": IntOnCount,
    "debug-print": DebugPrint,
    "while-tick": WhileTick,
    "weak-state": WeakState,
    "clean": Clean,
}
