"""Property-based fuzz of the fault-injection plane: under RANDOM chaos
schedules the engine must preserve its two load-bearing invariants —

1. **flow conservation**: sent = delivered + in-flight + dropped +
   rejected + fault_dropped, cumulatively exact, whatever the schedule
   kills, purges, delays or revives;
2. **termination**: a barrier plan written against the live membership
   view finishes (or dies by schedule) well under the tick budget —
   no schedule may deadlock the run;

plus the replayability property the plane is named for: the same seed +
schedule produces a byte-identical per-tick telemetry counter stream.

Gated on hypothesis like test_sync_fuzz / test_transport_fuzz. The
instance count and chunk are FIXED so the example budget buys schedule
diversity, not recompiles of new shapes (mask values still recompile —
that is the price of static schedules — hence the small max_examples)."""

import jax.numpy as jnp
import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property-based tier needs hypothesis"
)
from hypothesis import given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from testground_tpu.api import RunGroup  # noqa: E402
from testground_tpu.sim.api import (  # noqa: E402
    RUNNING,
    SUCCESS,
    Outbox,
    SimTestcase,
)
from testground_tpu.sim.engine import SimProgram, build_groups  # noqa: E402
from testground_tpu.sim.faults import build_fault_schedule  # noqa: E402

N = 6  # fixed shape: examples vary the schedule, not the program size
MAX_TICKS = 2048


class _BarrierTraffic(SimTestcase):
    """Signal → live-degraded barrier → DURATION ticks of rotating
    traffic → SUCCESS. Every instance that stays RUNNING terminates in
    bounded time; restarts re-run the pipeline from scratch."""

    STATES = ["go"]
    MSG_WIDTH = 1
    OUT_MSGS = 1
    IN_MSGS = 8
    MAX_LINK_TICKS = 8
    SHAPING = ("latency",)
    DURATION = 24

    def init(self, env):
        return {"k": jnp.int32(0), "passed": jnp.asarray(False)}

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        n = env.test_instance_count
        already = sync.last_seq[self.state_id("go")] > 0
        counts = sync.counts[self.state_id("go")]
        passed = state["passed"] | (
            (counts > 0) & (counts >= jnp.sum(sync.live))
        )
        k = jnp.where(passed, state["k"] + 1, state["k"])
        return self.out(
            {"k": k, "passed": passed},
            status=jnp.where(k >= cls.DURATION, SUCCESS, RUNNING),
            outbox=Outbox.single(
                jnp.mod(env.global_seq + 1 + t, n),
                jnp.zeros((1,), jnp.int32),
                passed,
                cls.OUT_MSGS,
                cls.MSG_WIDTH,
            ),
            signals=self.signal("go") * ~already,
        )


@st.composite
def fault_schedules(draw):
    """0–6 random events over the first ~80 ticks, every kind, random
    range targets (tick_ms = 1 so ms == ticks)."""
    events = []
    for _ in range(draw(st.integers(0, 6))):
        kind = draw(st.sampled_from(
            ["crash", "restart", "partition", "link_flap",
             "latency_spike", "loss_burst"]
        ))
        lo = draw(st.integers(0, N - 1))
        hi = draw(st.integers(lo + 1, N))
        # crash on even ticks, restart on odd: a crash and a restart of
        # the same instance on the SAME tick is refused at lowering
        # (the restart would be lost), so keep the streams disjoint
        start = draw(st.integers(0, 30))
        if kind == "crash":
            start = 2 * start
        elif kind == "restart":
            start = 2 * start + 1
        else:
            start = draw(st.integers(0, 60))
        e = {
            "kind": kind,
            "instances": f"{lo}:{hi}",
            "start_ms": float(start),
        }
        if kind == "partition":
            # the other side: a range disjoint from [lo, hi)
            side = draw(st.booleans())
            if side and lo > 0:
                e["to_instances"] = f"0:{lo}"
            elif hi < N:
                e["to_instances"] = f"{hi}:{N}"
            else:
                continue  # full-range primary: no disjoint side exists
            e["duration_ms"] = float(draw(st.integers(1, 20)))
            e["bidirectional"] = draw(st.booleans())
        elif kind == "link_flap":
            e["duration_ms"] = float(draw(st.integers(1, 20)))
            period = draw(st.integers(0, 6))
            if period:
                e["period_ms"] = float(period)
                e["duty"] = draw(
                    st.sampled_from([0.0, 0.25, 0.5, 0.75])
                )
        elif kind == "latency_spike":
            e["duration_ms"] = float(draw(st.integers(1, 20)))
            e["latency_ms"] = float(draw(st.integers(1, 5)))
        elif kind == "loss_burst":
            e["duration_ms"] = float(draw(st.integers(1, 20)))
            e["loss"] = float(draw(st.sampled_from([25.0, 50.0, 100.0])))
        events.append(e)
    return events


@settings(max_examples=8, deadline=None)
@given(fault_schedules(), st.integers(0, 2**31 - 1))
def test_conservation_and_termination_under_random_chaos(events, seed):
    groups = build_groups(
        [RunGroup(id="all", instances=N, parameters={})]
    )
    faults = build_fault_schedule(groups, {"all": events}, 1.0)
    prog = SimProgram(
        _BarrierTraffic(), groups, chunk=16, telemetry=True, faults=faults
    )

    def run_once():
        blocks = []
        res = prog.run(
            seed=seed,
            max_ticks=MAX_TICKS,
            telemetry_cb=lambda b: blocks.append(np.asarray(b).copy()),
        )
        return res, np.concatenate(blocks)

    res, stream = run_once()

    # -- termination: no schedule may deadlock the barrier plan. Every
    # instance ends SUCCESS or (crashed, never restarted) CRASH; the run
    # ends on the done flag, far below the tick budget.
    assert not (np.asarray(res["status"]) == RUNNING).any(), res["status"]
    assert res["ticks"] < MAX_TICKS

    # -- flow conservation, cumulatively exact under chaos
    assert res["msgs_sent"] == (
        res["msgs_delivered"]
        + res["cal_depth"]
        + res["msgs_dropped"]
        + res["msgs_rejected"]
        + res["fault_dropped"]
    ), dict(res=({k: res[k] for k in (
        "msgs_sent", "msgs_delivered", "cal_depth", "msgs_dropped",
        "msgs_rejected", "fault_dropped")}), events=events)

    # -- the telemetry stream's per-tick deltas sum to the same totals
    from testground_tpu.sim.telemetry import TELEMETRY_FIXED_COLUMNS

    col = {c: i for i, c in enumerate(TELEMETRY_FIXED_COLUMNS)}
    live_rows = stream[stream[:, col["tick"]] >= 0]
    assert int(live_rows[:, col["fault_dropped"]].sum()) == res[
        "fault_dropped"
    ]
    assert int(live_rows[:, col["faults_crashed"]].sum()) == res[
        "faults_crashed"
    ]

    # -- determinism: the same seed + schedule replays bit-identically
    res2, stream2 = run_once()
    assert np.array_equal(stream, stream2)
    assert res2["ticks"] == res["ticks"]
    for key in (
        "msgs_sent",
        "msgs_delivered",
        "msgs_dropped",
        "fault_dropped",
        "faults_crashed",
        "faults_restarted",
    ):
        assert res2[key] == res[key], key


@settings(max_examples=4, deadline=None)
@given(fault_schedules(), st.integers(0, 2**31 - 1))
def test_pallas_transport_bit_equal_under_random_chaos(events, seed):
    """ISSUE 5 equality pin, chaos edition: the SAME random schedule +
    seed through `transport="pallas"` (hand-tiled commit/pop kernels,
    interpret mode on CPU) and `transport="xla"` must produce identical
    telemetry streams and flow totals — fault kills land inside enqueue,
    exactly where the pallas kernel replaces the plane scatters. Few
    examples: each compiles BOTH backends' programs."""
    groups = build_groups(
        [RunGroup(id="all", instances=N, parameters={})]
    )
    faults = build_fault_schedule(groups, {"all": events}, 1.0)

    def run(transport):
        prog = SimProgram(
            _BarrierTraffic(),
            groups,
            chunk=16,
            telemetry=True,
            faults=faults,
            transport=transport,
        )
        blocks = []
        res = prog.run(
            seed=seed,
            max_ticks=MAX_TICKS,
            telemetry_cb=lambda b: blocks.append(np.asarray(b).copy()),
        )
        return res, np.concatenate(blocks)

    res_x, stream_x = run("xla")
    res_p, stream_p = run("pallas")
    assert np.array_equal(stream_x, stream_p)
    for key in (
        "ticks",
        "msgs_sent",
        "msgs_delivered",
        "msgs_enqueued",
        "msgs_dropped",
        "msgs_rejected",
        "cal_depth",
        "fault_dropped",
        "faults_crashed",
        "faults_restarted",
    ):
        assert res_p[key] == res_x[key], key
    assert np.array_equal(
        np.asarray(res_x["status"]), np.asarray(res_p["status"])
    )
    assert np.array_equal(
        np.asarray(res_x["finished_at"]), np.asarray(res_p["finished_at"])
    )
