"""placebo, sim edition: the do-nothing fixtures as vmappable state machines.

Sim twin of ``plans/placebo/main.go`` (ok / abort / panic / stall /
metrics): the smallest possible testcases, used to validate the ``sim:jax``
runner's outcome plumbing the way the reference's integration scripts 03-05
use placebo against local runners.
"""

import jax.numpy as jnp

from testground_tpu.sim.api import (
    CRASH,
    FAILURE,
    RUNNING,
    SUCCESS,
    SimTestcase,
)


class Ok(SimTestcase):
    def step(self, env, state, inbox, sync, t):
        return self.out(state, status=SUCCESS)


class Abort(SimTestcase):
    """record_failure + error return (integration test 14 semantics)."""

    def step(self, env, state, inbox, sync, t):
        return self.out(state, status=FAILURE)


class Panic(SimTestcase):
    def step(self, env, state, inbox, sync, t):
        return self.out(state, status=CRASH)


class Stall(SimTestcase):
    """Never terminates — exercises the max_ticks budget the way the
    reference's 24h sleep exercises the 10-min task timeout."""

    def step(self, env, state, inbox, sync, t):
        return self.out(state, status=RUNNING)


class Silent(SimTestcase):
    """Never emits a terminal status — the sim twin of the exec
    edition's silent ``os._exit(0)`` (issue-1349): the run ends at
    max_ticks with the instance still RUNNING, judged incomplete, and
    the run fails. Surfaced missing by ``tg check --trace-plans``
    (rule plan.load-failed): the manifest declared the case but the sim
    module never exposed it."""

    def step(self, env, state, inbox, sync, t):
        return self.out(state, status=RUNNING)


class OptionalFailure(SimTestcase):
    """Per-run failure knob (the ``issue-1493-optional-failure`` analog):
    ``should_fail`` is a group parameter, so it is a trace-time constant —
    no data-dependent control flow enters the compiled step."""

    def init(self, env):
        self.should_fail = (
            env.group.params.get("should_fail", "") == "true"
        )
        return {}

    def step(self, env, state, inbox, sync, t):
        return self.out(
            state, status=FAILURE if self.should_fail else SUCCESS
        )


class Metrics(SimTestcase):
    """Counts to 10 across ticks, then succeeds; the counter lands in each
    instance's metrics.out via collect_metrics."""

    def init(self, env):
        return {"counter": jnp.int32(0)}

    def step(self, env, state, inbox, sync, t):
        counter = state["counter"] + 1
        done = counter >= 10
        return self.out(
            {"counter": counter},
            status=jnp.where(done, SUCCESS, RUNNING),
        )

    def collect_metrics(self, group, final_state, status):
        return {"placebo.counter": final_state["counter"]}


sim_testcases = {
    "ok": Ok,
    "abort": Abort,
    "panic": Panic,
    "stall": Stall,
    "silent": Silent,
    "optional-failure": OptionalFailure,
    "metrics": Metrics,
}
