"""placebo: the do-nothing fixture plan.

Port of the reference's ``plans/placebo/main.go`` testcases (ok / panic /
stall, plus abort and metrics declared in its manifest): the ladder's basic
success/failure/timeout fixtures used by the integration suite
(``integration_tests/03-05``, 14, 16).
"""

import time

from testground_tpu.sdk import invoke_map


def ok(runenv):
    runenv.record_message("placebo is fine")


def abort(runenv):
    """Failure via explicit record + error return (integration test 14:
    silent failure must still fail the run)."""
    runenv.record_message("about to abort")
    return "aborting on purpose"


def panic(runenv):
    raise RuntimeError("this is an intentional panic")


def stall(runenv):
    """Stalls until the task timeout kills the run
    (``placebo/main.go`` stall sleeps 24h)."""
    runenv.record_message("Now stalling for 24 hours")
    time.sleep(24 * 3600)


def metrics(runenv):
    c = runenv.R().counter("placebo.counter")
    h = runenv.R().histogram("placebo.histogram")
    for i in range(10):
        c.inc(1)
        h.update(float(i))
    runenv.R().record_point("placebo.point", 42.0)


if __name__ == "__main__":
    invoke_map(
        {
            "ok": ok,
            "abort": abort,
            "panic": panic,
            "stall": stall,
            "metrics": metrics,
        }
    )
