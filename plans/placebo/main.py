"""placebo: the do-nothing fixture plan.

Port of the reference's ``plans/placebo/main.go`` testcases (ok / panic /
stall, plus abort and metrics declared in its manifest): the ladder's basic
success/failure/timeout fixtures used by the integration suite
(``integration_tests/03-05``, 14, 16).
"""

import os
import time

from testground_tpu.sdk import invoke_map


def ok(runenv):
    runenv.record_message("placebo is fine")


def abort(runenv):
    """Failure via explicit record + error return (integration test 14:
    silent failure must still fail the run)."""
    runenv.record_message("about to abort")
    return "aborting on purpose"


def panic(runenv):
    raise RuntimeError("this is an intentional panic")


def stall(runenv):
    """Stalls until the task timeout kills the run
    (``placebo/main.go`` stall sleeps 24h)."""
    runenv.record_message("Now stalling for 24 hours")
    time.sleep(24 * 3600)


def optional_failure(runenv):
    """Fails only when the run sets ``should_fail`` — the per-run knob the
    multi-run suite flips (reference: the ``issue-1493-optional-failure``
    testcase of ``plans/_integrations_runs``, driven by
    ``integration_tests/1493_continue_on_failure.sh``)."""
    if runenv.test_instance_params.get("should_fail", "") == "true":
        return "failing because should_fail is set"
    runenv.record_message("should_fail not set; succeeding")


def silent(runenv):
    """Exits without emitting a TERMINAL event (the start event has
    already been flushed by invoke_map). The runner must judge the
    instance incomplete and fail the run (reference: issue-1349,
    ``integration_tests/14_docker_silent_test_failure.sh``)."""
    os._exit(0)


def metrics(runenv):
    c = runenv.R().counter("placebo.counter")
    h = runenv.R().histogram("placebo.histogram")
    for i in range(10):
        c.inc(1)
        h.update(float(i))
    runenv.R().record_point("placebo.point", 42.0)


if __name__ == "__main__":
    invoke_map(
        {
            "ok": ok,
            "abort": abort,
            "panic": panic,
            "stall": stall,
            "optional-failure": optional_failure,
            "silent": silent,
            "metrics": metrics,
        }
    )
