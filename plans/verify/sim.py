"""verify plan, sim edition.

Sim twin of the reference's ``plans/verify`` (``main.go:37-40``
UsesDataNetwork): the framework-invariant plan. The reference elects one
target instance, publishes the target's addresses on every network, then
has the other instances ping each address — failing if the control network
answers or the data network loses packets. Here the invariant becomes: **a
message reaches an instance only through the shaped data-plane transport,
checksum-exact as the link model delivered it** —

- ``uses-data-network``: the target (SignalEntry rank 1 on "ready", the
  ``MustSignalAndWait`` switch at ``main.go:63``) publishes two addresses
  on the "addrs" topic: its data-plane address (its instance index) and a
  control-plane address (index + N, outside the data plane — the
  192.18.x.x analog). Pingers ping both, staggered one pinger per tick.
  Data pings must all return as checksum-verified pongs (packet loss 0%);
  control pings must return nothing (the control address is unreachable
  via the transport). Every receiver validates each inbox entry's
  checksum against its provenance — any corruption, forged sender, or
  out-of-plane delivery is a FAILURE.
- ``uses-data-network-drop``: the DROP-all invariant. Every pinger
  installs a BLACKHOLE filter over all regions before pinging; the target
  must receive ZERO messages and the pingers ZERO pongs for the whole
  run (the sidecar's Drop route: ``link.go:187-217``). Sync traffic still
  flows — coordination rides the control plane, exactly like the
  reference's Redis sync on the control network.
"""

import jax.numpy as jnp

from testground_tpu.sim.api import (
    FAILURE,
    FILTER_DROP,
    RUNNING,
    SUCCESS,
    Outbox,
    SimTestcase,
)

PING = 1
PONG = 2
END_OF_NETWORKS = -1  # the "endOfNetworks" sentinel (main.go:60)

GOLD = -1640531527  # 0x9E3779B9 as int32 — checksum mixing constant


def _checksum(src, seq):
    """Payload checksum keyed on sender identity + sequence: in-flight
    corruption or forged provenance breaks it (int32 wraparound arithmetic
    keeps it traceable)."""
    return (src * jnp.int32(GOLD)) ^ (seq + jnp.int32(0x5EED))


class UsesDataNetwork(SimTestcase):
    STATES = ["ready", "target-ready", "finished"]
    TOPICS = ["addrs"]
    MSG_WIDTH = 3  # [kind, checksum, seq]
    OUT_MSGS = 4  # target echoes a full inbox; pingers use slots 0-1
    IN_MSGS = 4
    PUB_WIDTH = 2  # [addr, is_end]
    SUB_K = 4
    MAX_LINK_TICKS = 4
    SHAPING = ("latency", "filters")
    DROP_ALL = False  # the -drop testcase flips this
    # in-flight pongs settle before the loss verdict: a full round trip is
    # at most 2·(MAX_LINK_TICKS-1) hops (per-hop delay clamps to the
    # horizon), +2 for the target's processing tick and the verdict tick
    DRAIN_TICKS = 2 * (MAX_LINK_TICKS - 1) + 2

    def init(self, env):
        return {
            "addr_data": jnp.int32(-1),
            "addr_ctrl": jnp.int32(-1),
            "addrs_seen": jnp.int32(0),
            "pub_idx": jnp.int32(0),
            "sent": jnp.int32(0),
            "done_at": jnp.int32(-1),
            "pongs_data": jnp.int32(0),
            "recv": jnp.int32(0),
            "bad": jnp.asarray(False),
            "sig_finished": jnp.asarray(False),
        }

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        n = env.test_instance_count
        pings = (
            env.int_param("pings") if "pings" in env.group.params else 8
        )

        rank = sync.last_seq[self.state_id("ready")]
        is_target = rank == 1
        is_pinger = rank > 1
        me = env.global_seq

        # ------------------------------------------------- inbox validation
        kind = inbox.word(0)
        csum = inbox.word(1)
        seq = inbox.word(2)
        ok_sum = csum == _checksum(inbox.src, seq)
        got_ping = inbox.valid & (kind == PING)
        got_pong = inbox.valid & (kind == PONG)
        # the core invariant: everything delivered must carry a valid
        # checksum from its true sender — "inbox content is exactly what
        # the link model delivered"
        bad = state["bad"] | jnp.any(inbox.valid & ~ok_sum)

        # --------------------------------------------------- target: publish
        # addrs entries over 3 ticks: data addr, control addr, END
        entries = jnp.stack(
            [
                jnp.stack([me, jnp.int32(0)]),
                jnp.stack([me + n, jnp.int32(0)]),
                jnp.stack([jnp.int32(END_OF_NETWORKS), jnp.int32(1)]),
            ]
        )
        can_pub = is_target & (state["pub_idx"] < 3) & (t >= 1)
        pub_payload = entries[jnp.minimum(state["pub_idx"], 2)][None, :]
        pub_idx = state["pub_idx"] + can_pub.astype(jnp.int32)
        sig_target_ready = is_target & (pub_idx >= 3) & (state["pub_idx"] < 3)

        # target echoes every valid ping back to its sender, re-stamped
        # with the target's own provenance (so the pinger's generic
        # checksum validation covers the return path too)
        echo = Outbox(
            dst=inbox.src,
            payload=jnp.stack(
                [jnp.full_like(kind, PONG), _checksum(me, seq), seq],
                axis=-1,
            ),
            valid=got_ping & is_target & ok_sum,
        )
        recv = state["recv"] + jnp.sum(got_ping.astype(jnp.int32))

        # ------------------------------------------------- pinger: subscribe
        sub_pay = sync.sub_payload[0]  # [SUB_K, PUB_WIDTH]
        sub_val = sync.sub_valid[0]  # [SUB_K]
        target_ready = sync.counts[self.state_id("target-ready")] >= 1
        k_idx = jnp.arange(cls.SUB_K, dtype=jnp.int32)
        take = sub_val & (k_idx < 3 - state["addrs_seen"]) & is_pinger
        ent_idx = state["addrs_seen"] + k_idx
        is_data = take & (ent_idx == 0)
        is_ctrl = take & (ent_idx == 1)
        addr_data = jnp.where(
            jnp.any(is_data),
            jnp.sum(jnp.where(is_data, sub_pay[:, 0], 0)),
            state["addr_data"],
        )
        addr_ctrl = jnp.where(
            jnp.any(is_ctrl),
            jnp.sum(jnp.where(is_ctrl, sub_pay[:, 0], 0)),
            state["addr_ctrl"],
        )
        ncons = jnp.sum(take.astype(jnp.int32))
        addrs_seen = state["addrs_seen"] + ncons

        # --------------------------------------------------- pinger: pinging
        have_addrs = addrs_seen >= 3
        # staggered: pinger fires on ticks ≡ its index (mod N), bounding
        # target fan-in to ~1 ping/tick at any instance count
        my_slot = jnp.mod(t, n) == jnp.mod(me, n)
        send = (
            is_pinger
            & have_addrs
            & my_slot
            & (state["sent"] < pings)
            & target_ready
        )
        pseq = state["sent"]
        sent = state["sent"] + send.astype(jnp.int32)
        done_at = jnp.where(
            (state["done_at"] < 0) & (sent >= pings), t, state["done_at"]
        )

        ob = Outbox.empty(cls.OUT_MSGS, cls.MSG_WIDTH)
        ping_payload = jnp.stack([jnp.int32(PING), _checksum(me, pseq), pseq])
        # slot 0: ping the data address; slot 1: ping the control address
        # (out-of-plane — the transport must never deliver it)
        ob = Outbox(
            dst=ob.dst.at[0].set(addr_data).at[1].set(addr_ctrl),
            payload=ob.payload.at[0].set(ping_payload).at[1].set(ping_payload),
            valid=ob.valid.at[0].set(send).at[1].set(send),
        )
        outbox = Outbox(
            dst=jnp.where(is_target, echo.dst, ob.dst),
            payload=jnp.where(is_target, echo.payload, ob.payload),
            valid=jnp.where(is_target, echo.valid, ob.valid),
        )

        pongs_data = state["pongs_data"] + jnp.sum(
            (got_pong & ok_sum).astype(jnp.int32)
        )

        # ------------------------------------------------------- the verdict
        expected = jnp.int32(0 if cls.DROP_ALL else 1) * pings
        pinger_done = (done_at >= 0) & (t >= done_at + cls.DRAIN_TICKS)
        pinger_ok = pinger_done & (pongs_data == expected)
        pinger_bad = pinger_done & (pongs_data != expected)
        # a control-ping delivery would double-count into pongs_data
        # (> expected) or surface as an unknown-provenance checksum (bad)

        fin_target = jnp.int32(0 if cls.DROP_ALL else 1) * (n - 1) * pings
        target_bad = is_target & (recv > fin_target)

        sig_finished = (pinger_ok | (is_target & (t >= 1))) & ~state[
            "sig_finished"
        ]
        all_done = sync.counts[self.state_id("finished")] >= n

        status = jnp.where(
            bad | pinger_bad | target_bad,
            FAILURE,
            jnp.where(all_done, SUCCESS, RUNNING),
        )

        # DROP-all: install a BLACKHOLE toward every region the tick rank
        # becomes known, before any ping flies (uses-data-network-drop)
        drop_filters = jnp.full((len(env.groups),), FILTER_DROP, jnp.int32)

        return self.out(
            {
                "addr_data": addr_data,
                "addr_ctrl": addr_ctrl,
                "addrs_seen": addrs_seen,
                "pub_idx": pub_idx,
                "sent": sent,
                "done_at": done_at,
                "pongs_data": pongs_data,
                "recv": recv,
                "bad": bad,
                "sig_finished": state["sig_finished"] | sig_finished,
            },
            status=status,
            outbox=outbox,
            signals=self.signal("ready") * (t == 0)
            + self.signal("target-ready") * sig_target_ready
            + self.signal("finished") * sig_finished,
            pub_payload=pub_payload,
            pub_valid=jnp.asarray([can_pub]),
            sub_consume=jnp.asarray([ncons]),
            net_filters=drop_filters if cls.DROP_ALL else None,
            net_filters_valid=((t == 1) & is_pinger) if cls.DROP_ALL else False,
        )

    def collect_metrics(self, group, final_state, status):
        return {
            "pongs_received": final_state["pongs_data"],
            "pings_delivered_to_target": final_state["recv"],
        }


class UsesDataNetworkDrop(UsesDataNetwork):
    """DROP-all variant: with a BLACKHOLE over every route, the transport
    must deliver nothing — zero pongs at pingers, zero pings at the target
    (the DROP_ALL expectations in the verdict logic)."""

    DROP_ALL = True


sim_testcases = {
    "uses-data-network": UsesDataNetwork,
    "uses-data-network-drop": UsesDataNetworkDrop,
}
