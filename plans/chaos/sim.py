"""chaos plan: the fault-injection plane's end-to-end exercise.

No reference twin — this plan exists for what the reference could only
do with a human driving the sidecar: a **scheduled nemesis** run
(docs/FAULTS.md). The composition declares the chaos
(``[[groups.run.faults]]`` — see ``_compositions/smoke.toml``); the plan
is an ordinary cooperative state machine that SURVIVES it:

1. everyone signals ``start`` and waits at a barrier written against the
   sync plane's live-membership view (``counts >= Σ sync.live`` —
   ``sim/sync_kernel.live_per_group``). The composition crashes a slice
   of instances *mid-barrier*; the target degrades the same tick and the
   survivors proceed instead of deadlocking — the headline behavior.
   A ``slow_count`` prefix of instances holds its signal until
   ``slow_tick`` so the barrier is genuinely blocked on them when the
   scheduled crash takes them out.
2. a pipelined probe sweep (one probe per tick at peer
   ``(me + 1 + k) mod n``, fan-in bounded like splitbrain's) generates
   traffic through the scheduled link flaps and the partition window —
   every kill lands in the ``fault_dropped`` counter, keeping flow
   conservation exact.
3. restarted instances come back through ``init`` with their sync
   history intact: ``last_seq`` says whether they already signalled, so
   nobody double-signals, and they rejoin mid-run.
4. from ``heal_tick`` (chosen after the partition heals) every instance
   probes its partner ``(me + n//2) mod n`` across the old partition
   boundary, resending every few ticks. SUCCESS requires BOTH sides of
   the handshake — a heal reply received AND a heal probe answered — so
   nobody freezes while a slower peer (e.g. a late restart still
   finishing its sweep) has yet to probe it; the pairing is a
   permutation, so the handshake closes for every cycle. No handshake
   by ``deadline`` is a FAILURE, so a heal that didn't happen fails the
   run loudly instead of stalling to max_ticks.

Pair every scheduled crash with a restart comfortably before
``deadline``: the heal handshake needs both partners alive (a dead
partner fails its peer at the deadline — which is itself a useful chaos
assertion).
"""

import jax.numpy as jnp

from testground_tpu.sim.api import (
    FAILURE,
    RUNNING,
    SUCCESS,
    Outbox,
    SimTestcase,
)

PROBE = 1
REPLY = 2

# phases
P_START = 0  # signal "start" (slow instances hold until slow_tick)
P_WAIT = 1  # live-degraded barrier
P_PROBE = 2  # pipelined probe sweep (traffic through the chaos windows)
P_HEAL = 3  # cross-partition heal handshake
P_DONE = 4

_HEAL_EVERY = 4  # heal-probe resend cadence in ticks


class ChaosBarrier(SimTestcase):
    STATES = ["start"]
    MSG_WIDTH = 2  # word0: kind, word1: probe id (sweep k, or n = heal)
    OUT_MSGS = 2  # slot 0: reply, slot 1: own probe
    IN_MSGS = 8
    MAX_LINK_TICKS = 8
    SHAPING = ("latency",)

    def init(self, env):
        z = jnp.int32(0)
        return {
            "phase": z,
            "k": z,  # next sweep probe index
            "replies": z,  # sweep replies received (metric only)
            "heal_got": jnp.asarray(False),
            # answered the prober whose partner is me — success requires
            # BOTH sides of the handshake, so nobody freezes while its
            # peer still needs a reply (a late restart may enter the
            # heal phase ticks after its partner — docstring point 4)
            "heal_answered": jnp.asarray(False),
        }

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        n = env.test_instance_count

        def p(name, default):
            return (
                env.int_param(name)
                if name in env.group.params
                else default
            )

        slow_count = p("slow_count", 2)
        slow_tick = p("slow_tick", 30)
        heal_tick = p("heal_tick", 44)
        deadline = p("deadline", 120)

        phase = state["phase"]

        # --- serve replies in every phase (the reference's HTTP server
        # runs for the whole test body): answer the first probe in the
        # inbox, echoing its id back to its sender
        kind = inbox.word(0)
        pid = inbox.word(1)
        is_probe = inbox.valid & (kind == PROBE)
        got_reply = inbox.valid & (kind == REPLY)
        probe_slot = jnp.argmax(is_probe)
        send_reply = jnp.any(is_probe)
        reply_to = inbox.src[probe_slot]
        reply_id = pid[probe_slot]

        # --- phase START: signal once (a restarted instance re-enters
        # here with its sync history intact — last_seq > 0 means its
        # earlier signal still stands, so it must not signal again)
        ready = (env.global_seq >= slow_count) | (t >= slow_tick)
        already = sync.last_seq[self.state_id("start")] > 0
        do_signal = (phase == P_START) & ready & ~already
        leave_start = (phase == P_START) & ready

        # --- phase WAIT: the live-degraded barrier — the target is the
        # CURRENT live membership, so a mid-barrier crash shrinks it and
        # unblocks the survivors the same tick (docs/FAULTS.md)
        counts = sync.counts[self.state_id("start")]
        live_total = jnp.sum(sync.live)
        barrier_open = (counts > 0) & (counts >= live_total)
        leave_wait = (phase == P_WAIT) & barrier_open

        # --- phase PROBE: pipelined sweep, one probe per tick at peer
        # (me + 1 + k) mod n — bounded fan-in traffic that rides through
        # the scheduled flap/partition windows
        k = state["k"]
        rounds = n - 1
        probing = (phase == P_PROBE) & (k < rounds)
        sweep_target = jnp.mod(env.global_seq + 1 + k, n)
        k_next = jnp.where(probing, k + 1, k)
        leave_probe = (phase == P_PROBE) & (k >= rounds)
        replies = state["replies"] + jnp.sum(got_reply.astype(jnp.int32))

        # --- phase HEAL: from heal_tick, probe the partner across the
        # old partition boundary until answered (resend every few ticks
        # in global lockstep so partner pairs succeed symmetrically)
        partner = jnp.mod(env.global_seq + n // 2, n)
        heal_got = state["heal_got"] | jnp.any(got_reply & (pid == n))
        # answering a HEAL probe counts in any phase (the prober may be
        # ticks ahead of us); only the probe we actually reply to counts
        heal_answered = state["heal_answered"] | (
            send_reply & (reply_id == n)
        )
        heal_probe = (
            (phase == P_HEAL)
            & ~heal_got
            & (t >= heal_tick)
            & (jnp.mod(t - heal_tick, _HEAL_EVERY) == 0)
        )
        done_heal = heal_got & heal_answered
        finish = (phase == P_HEAL) & done_heal
        timed_out = (phase == P_HEAL) & ~done_heal & (t >= deadline)

        new_phase = jnp.where(
            leave_start,
            P_WAIT,
            jnp.where(
                leave_wait,
                P_PROBE,
                jnp.where(
                    leave_probe,
                    P_HEAL,
                    jnp.where(finish, P_DONE, phase),
                ),
            ),
        ).astype(jnp.int32)
        status = jnp.where(
            timed_out, FAILURE, jnp.where(finish, SUCCESS, RUNNING)
        ).astype(jnp.int32)

        send_probe = probing | heal_probe
        probe_dst = jnp.where(heal_probe, partner, sweep_target)
        probe_id = jnp.where(heal_probe, jnp.int32(n), k)
        ob = Outbox.empty(cls.OUT_MSGS, cls.MSG_WIDTH)
        ob = Outbox(
            dst=ob.dst.at[0].set(reply_to).at[1].set(probe_dst),
            payload=ob.payload.at[0, 0]
            .set(REPLY)
            .at[0, 1]
            .set(reply_id)
            .at[1, 0]
            .set(PROBE)
            .at[1, 1]
            .set(probe_id),
            valid=ob.valid.at[0].set(send_reply).at[1].set(send_probe),
        )

        return self.out(
            {
                "phase": new_phase,
                "k": k_next,
                "replies": replies,
                "heal_got": heal_got,
                "heal_answered": heal_answered,
            },
            status=status,
            outbox=ob,
            signals=self.signal("start") * do_signal,
        )

    def collect_metrics(self, group, final_state, status):
        return {
            "chaos.replies": final_state["replies"],
            "chaos.healed": final_state["heal_got"],
        }


sim_testcases = {"chaos-barrier": ChaosBarrier}
