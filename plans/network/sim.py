"""network plan, sim edition.

Sim twin of the reference's ``plans/network`` testcases:

- ``ping-pong`` (``pingpong.go``): pairs shape their egress latency, barrier,
  exchange ping/pong, assert the measured RTT lands in the shaped window,
  reconfigure to a lower latency mid-run and assert again — the canonical
  proof that link shaping + dynamic reconfiguration behave. RTTs here are in
  **simulated** time, so the windows are exact up to tick quantization.
- ``traffic-allowed`` / ``traffic-blocked`` (``traffic.go:16-46``): every
  instance sends to its ring successor under an Accept vs Drop filter and
  asserts traffic did / did not flow.
- ``traffic-shaped``: a one-tick burst through an HTB-shaped link
  (``link.go:155-183`` bandwidth semantics) asserting conservation and
  exact per-tick pacing in simulated time.
- ``traffic-ruled``: ring traffic cut mid-run by per-instance RANGE
  RULES (``link.go:187-217`` — each instance reconfiguring its own
  subnet-rule list), asserting the one-tick turnaround, the REJECT
  feedback, and untouched traffic before the cut — at any scale
  (O(N·K), PERF.md r5).

Instances pair/chain by global sequence number; all control flow is
``jnp.where`` over int32 state so the whole case vmaps and jits.
"""

import jax.numpy as jnp
import numpy as np

from testground_tpu.sim.net import SHAPING_NO_DUPLICATE
from testground_tpu.sim.api import (
    FAILURE,
    FILTER_ACCEPT,
    FILTER_DROP,
    FILTER_REJECT,
    RUNNING,
    SUCCESS,
    Outbox,
    SimTestcase,
)

PING = 1
PONG = 2


class PingPong(SimTestcase):
    STATES = ["ready", "half-done"]
    MSG_WIDTH = 2  # word0: kind, word1: round
    OUT_MSGS = 2  # slot 0: pong replies, slot 1: our own pings
    IN_MSGS = 4
    MAX_LINK_TICKS = 512  # upper bound; narrowed per run below
    # the case shapes latency only (plus the dynamic mid-run reshape);
    # duplicate-shaping stays undeclared like pingpong-sustained — its
    # second-copy pass would double the message axis for a feature this
    # plan never exercises
    SHAPING = SHAPING_NO_DUPLICATE

    @classmethod
    def specialize(cls, groups, tick_ms=1.0):
        """Size the calendar horizon to the run's shaped latencies instead
        of the 512-tick bound. The calendar is O(horizon · N · slots), so
        at large N the static bound is what limits instances per chip:
        with the default 100 ms latency this narrows 512 → 128 ticks and
        a 1M-instance ping-pong fits a single 16 GB chip."""
        lat = 0.0
        for g in groups:
            lat = max(
                lat,
                float(g.params.get("latency_ms", 100.0)),
                float(g.params.get("latency2_ms", 10.0)),
            )
        need = max(1, round(lat / tick_ms)) + 2  # delay + clamp headroom
        horizon = 8
        while horizon < need:
            horizon *= 2
        horizon = min(horizon, cls.MAX_LINK_TICKS)
        if horizon == cls.MAX_LINK_TICKS:
            return cls
        return type(
            f"{cls.__name__}_h{horizon}", (cls,), {"MAX_LINK_TICKS": horizon}
        )

    def init(self, env):
        z = jnp.int32(0)
        f = jnp.asarray(False)
        return {
            "phase": z,
            "start": z,
            "start2": z,
            "rtt1": jnp.int32(-1),
            "rtt2": jnp.int32(-1),
            "answered1": f,
            "got1": f,
            "answered2": f,
            "got2": f,
        }

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        n = env.test_instance_count
        lat1 = env.float_param("latency_ms") if "latency_ms" in env.group.params else 100.0
        lat2 = env.float_param("latency2_ms") if "latency2_ms" in env.group.params else 10.0
        tol = env.float_param("tolerance_ms") if "tolerance_ms" in env.group.params else 15.0
        partner = env.global_seq ^ 1
        # odd instance count: the last instance has no partner (partner == n,
        # whose sends the transport bounds-drops). It must not stall the
        # cohort — the half-done barrier waits for ALL n — so it sails
        # through every pair-gated phase and succeeds unconditionally.
        solo = partner >= n

        kind = inbox.payload[0]
        rnd = inbox.payload[1]
        v = inbox.valid

        def got(k, r):
            return jnp.any(v & (kind == k) & (rnd == r))

        phase = state["phase"]
        ready = sync.counts[self.state_id("ready")] >= n
        half = sync.counts[self.state_id("half-done")] >= n

        p0 = phase == 0
        send_ping1 = (phase == 1) & ready
        reply1 = got(PING, 1)  # always answer pings, whatever our phase
        reply2 = got(PING, 2)
        gp1 = (phase == 2) & got(PONG, 1)
        gp2 = (phase == 4) & got(PONG, 2)

        answered1 = state["answered1"] | reply1 | solo
        got1 = state["got1"] | gp1 | solo
        answered2 = state["answered2"] | reply2 | solo
        got2 = state["got2"] | gp2 | solo
        rtt1 = jnp.where(gp1, t - state["start"], state["rtt1"])
        rtt2 = jnp.where(gp2, t - state["start2"], state["rtt2"])
        fin1 = (phase == 2) & answered1 & got1
        send_ping2 = (phase == 3) & half
        fin2 = (phase == 4) & answered2 & got2

        new_phase = jnp.where(
            p0,
            1,
            jnp.where(
                send_ping1,
                2,
                jnp.where(
                    fin1, 3, jnp.where(send_ping2, 4, jnp.where(fin2, 5, phase))
                ),
            ),
        ).astype(jnp.int32)

        # --- RTT assertions (pingpong.go:185-195 windows, in sim time)
        rtt1_ms = rtt1.astype(jnp.float32) * env.tick_ms
        rtt2_ms = rtt2.astype(jnp.float32) * env.tick_ms
        ok = solo | (
            (rtt1_ms >= 2 * lat1)
            & (rtt1_ms <= 2 * lat1 + tol)
            & (rtt2_ms >= 2 * lat2)
            & (rtt2_ms <= 2 * lat2 + tol)
        )
        status = jnp.where(
            fin2, jnp.where(ok, SUCCESS, FAILURE), RUNNING
        ).astype(jnp.int32)

        # --- sends
        send_pong = reply1 | reply2
        pong_round = jnp.where(reply2, 2, 1).astype(jnp.int32)
        send_ping = send_ping1 | send_ping2
        ping_round = jnp.where(send_ping2, 2, 1).astype(jnp.int32)
        ob = Outbox.empty(cls.OUT_MSGS, cls.MSG_WIDTH)
        ob = Outbox(
            dst=ob.dst.at[0].set(partner).at[1].set(partner),
            payload=ob.payload.at[0, 0]
            .set(PONG)
            .at[0, 1]
            .set(pong_round)
            .at[1, 0]
            .set(PING)
            .at[1, 1]
            .set(ping_round),
            valid=ob.valid.at[0].set(send_pong).at[1].set(send_ping),
        )

        # --- network (re)configuration: shaped at start, reshaped at fin1
        shape1 = self.link_shape(latency_ms=lat1)
        shape2 = self.link_shape(latency_ms=lat2)

        return self.out(
            {
                "phase": new_phase,
                "start": jnp.where(send_ping1, t, state["start"]),
                "start2": jnp.where(send_ping2, t, state["start2"]),
                "rtt1": rtt1,
                "rtt2": rtt2,
                "answered1": answered1,
                "got1": got1,
                "answered2": answered2,
                "got2": got2,
            },
            status=status,
            outbox=ob,
            signals=self.signal("ready") * p0
            + self.signal("half-done") * fin1,
            net_shape=jnp.where(fin1, shape2, shape1),
            net_shape_valid=p0 | fin1,
        )

    def collect_metrics(self, group, final_state, status):
        return {
            "pingpong.rtt1_ticks": final_state["rtt1"],
            "pingpong.rtt2_ticks": final_state["rtt2"],
        }


class PingPongSustained(SimTestcase):
    """The headline full-path workload: paired ping-pong sustained for a
    fixed simulated duration with NONE of the fast-path shortcuts —
    general sorted slot assignment, sender provenance tracked, every
    LinkShape feature compiled in (zero rates, full machinery), live sync
    counters (each completed round signals "round"), and a periodic
    mid-run latency reshape through the dynamic net-config path.

    This is what BENCH reports as the primary number: the same transport
    semantics `plans/network` ping-pong exercises, held at full load for
    the whole run instead of finishing after two rounds (the plain
    ``ping-pong`` case at 100k is run alongside it as the correctness
    checkpoint). Reference behavior: ``pingpong.go`` + the reshape at
    ``pingpong.go:185-195``.
    """

    STATES = ["ready", "round"]
    MSG_WIDTH = 1  # kind and round packed: word0 = kind | round << 2
    OUT_MSGS = 2  # slot 0: pong replies, slot 1: own pings
    IN_MSGS = 4
    MAX_LINK_TICKS = 8  # covers the 4ms/2ms shaped latencies at 1ms ticks
    # deliberately general: sorted slot path, src plane on, and every
    # shaping feature except duplicate (whose second-copy pass doubles
    # the message axis; plans that shape duplicates declare it — none of
    # the reference network plans do)
    SHAPING = SHAPING_NO_DUPLICATE

    def init(self, env):
        z = jnp.int32(0)
        return {"rounds": z, "started": jnp.asarray(False), "shape_hi": z}

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        n = env.test_instance_count
        duration = (
            env.int_param("duration_ticks")
            if "duration_ticks" in env.group.params
            else 1000
        )
        lat1 = (
            env.float_param("latency_ms")
            if "latency_ms" in env.group.params
            else 4.0
        )
        lat2 = (
            env.float_param("latency2_ms")
            if "latency2_ms" in env.group.params
            else 2.0
        )
        reshape_every = (
            env.int_param("reshape_every")
            if "reshape_every" in env.group.params
            else 1000
        )
        partner = env.global_seq ^ 1
        # odd instance count: the unpaired last instance self-succeeds at
        # the deadline instead of failing with zero rounds (its sends to
        # the out-of-range partner are bounds-dropped by the transport)
        solo = partner >= n

        # only count messages from the partner (provenance check — the
        # reason this path keeps the src plane); word0 packs kind in the
        # low 2 bits and the round number above
        from_partner = inbox.valid & (inbox.src == partner)
        kind = inbox.payload[0] & 3
        got_ping = jnp.any(from_partner & (kind == PING))
        got_pong = jnp.any(from_partner & (kind == PONG))

        ready = sync.counts[self.state_id("ready")] >= n
        started = state["started"] | ready
        open_ping = ready & ~state["started"]

        rounds = state["rounds"] + got_pong.astype(jnp.int32)
        send_ping = open_ping | got_pong
        send_pong = got_ping

        done = t >= duration
        ok = solo | (rounds > 0)
        status = jnp.where(
            done, jnp.where(ok, SUCCESS, FAILURE), RUNNING
        ).astype(jnp.int32)

        ob = Outbox.empty(cls.OUT_MSGS, cls.MSG_WIDTH)
        ob = Outbox(
            dst=ob.dst.at[0].set(partner).at[1].set(partner),
            payload=ob.payload.at[0, 0]
            .set(PONG | (rounds << 2))
            .at[1, 0]
            .set(PING | (rounds << 2)),
            valid=ob.valid.at[0]
            .set(send_pong & ~done)
            .at[1]
            .set(send_ping & ~done),
        )

        # periodic reshape through the dynamic net-config path
        at_reshape = started & (jnp.mod(t, reshape_every) == 0) & (t > 0)
        shape_hi = jnp.where(
            at_reshape, 1 - state["shape_hi"], state["shape_hi"]
        )
        lat = jnp.where(shape_hi == 0, lat1, lat2)

        return self.out(
            {"rounds": rounds, "started": started, "shape_hi": shape_hi},
            status=status,
            outbox=ob,
            signals=self.signal("ready") * (t == 0)
            + self.signal("round") * got_pong,
            net_shape=self.link_shape(latency_ms=lat),
            net_shape_valid=(t == 0) | at_reshape,
        )

    def collect_metrics(self, group, final_state, status):
        return {"sustained.rounds": final_state["rounds"]}


class _Traffic(SimTestcase):
    """Ring traffic under an Accept (allowed) or Drop (blocked) filter."""

    STATES = ["net-ready"]
    BLOCKED = False
    MSG_WIDTH = 2
    OUT_MSGS = 1
    IN_MSGS = 4

    def init(self, env):
        return {
            "phase": jnp.int32(0),
            "deadline": jnp.int32(0),
            "received": jnp.int32(0),
        }

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        n = env.test_instance_count
        wait = (
            env.int_param("wait_ticks")
            if "wait_ticks" in env.group.params
            else 50
        )
        succ = jnp.mod(env.global_seq + 1, n)

        phase = state["phase"]
        ready = sync.counts[self.state_id("net-ready")] >= n
        p0 = phase == 0
        send = (phase == 1) & ready

        received = state["received"] + inbox.count
        deadline = jnp.where(send, t + wait, state["deadline"])
        judge = (phase == 2) & (t >= deadline)
        flowed = received > 0
        ok = flowed != cls.BLOCKED
        status = jnp.where(
            judge, jnp.where(ok, SUCCESS, FAILURE), RUNNING
        ).astype(jnp.int32)

        action = FILTER_DROP if cls.BLOCKED else FILTER_ACCEPT
        n_groups = len(env.groups)

        return self.out(
            {
                "phase": jnp.where(p0, 1, jnp.where(send, 2, phase)).astype(
                    jnp.int32
                ),
                "deadline": deadline,
                "received": received,
            },
            status=status,
            outbox=Outbox.single(
                succ, jnp.asarray([1, 0]), send, cls.OUT_MSGS, cls.MSG_WIDTH
            ),
            signals=self.signal("net-ready") * p0,
            net_filters=jnp.full((n_groups,), action, jnp.int32),
            net_filters_valid=p0,
        )

    def collect_metrics(self, group, final_state, status):
        return {"traffic.received": final_state["received"]}


class TrafficAllowed(_Traffic):
    BLOCKED = False


class TrafficBlocked(_Traffic):
    BLOCKED = True


class TrafficRuled(SimTestcase):
    """Ring traffic cut mid-run by a per-instance RANGE RULE — the
    "filter_rules" granularity model (the reference sidecar's
    per-instance subnet rules, ``pkg/sidecar/link.go:187-217``: each
    instance reconfigures its OWN rule list; a subnet is a contiguous
    index range under sequential addressing).

    Every instance streams to its ring successor; at ``cut_tick`` each
    instance installs a REJECT rule covering exactly its successor. The
    plan asserts three things the region table cannot express at scale:
    the rule applies from the next tick (deliveries stop at
    cut_tick + 1 + latency), the REJECT feeds back to the sender (the
    PROHIBIT analog), and traffic before the cut was untouched.
    """

    FILTER_RULES = 2
    MSG_WIDTH = 1
    OUT_MSGS = 1
    IN_MSGS = 4
    MAX_LINK_TICKS = 8
    SHAPING = ("latency", "filter_rules")
    DEFAULT_LINK = (1.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0)

    def init(self, env):
        return {
            "received": jnp.int32(0),
            "last_arrival": jnp.int32(-1),
            "rejected": jnp.int32(0),
        }

    def step(self, env, state, inbox, sync, t):
        n = env.test_instance_count
        cut = (
            env.int_param("cut_tick")
            if "cut_tick" in env.group.params
            else 8
        )
        stop = (
            env.int_param("stop_tick")
            if "stop_tick" in env.group.params
            else 24
        )
        succ = jnp.mod(env.global_seq + 1, n)

        received = state["received"] + inbox.count
        last = jnp.where(inbox.count > 0, t, state["last_arrival"])
        rejected = state["rejected"] + sync.rejected

        # sends at tick s arrive s + delay (delay = ceil(latency/tick),
        # static at trace time); the rule lands at cut's end, so the
        # last delivered send is the one at cut — cut+1 messages, last
        # arriving at cut + delay — and every later send REJECTs back
        delay = int(np.ceil(self.DEFAULT_LINK[0] / env.tick_ms))
        expect_recv = cut + 1
        expect_last = cut + delay
        expect_rej = stop - (cut + 1)
        judge = t >= stop + delay + 4
        ok = (
            (received == expect_recv)
            & (last == expect_last)
            & (rejected == expect_rej)
        )
        return self.out(
            {
                "received": received,
                "last_arrival": last,
                "rejected": rejected,
            },
            status=jnp.where(
                judge, jnp.where(ok, SUCCESS, FAILURE), RUNNING
            ).astype(jnp.int32),
            outbox=Outbox.single(succ, jnp.asarray([1]), t < stop, 1, 1),
            net_rules=self.filter_rules((succ, succ + 1, FILTER_REJECT)),
            net_rules_valid=t == cut,
        )

    def collect_metrics(self, group, final_state, status):
        return {
            "traffic.received": final_state["received"],
            "traffic.rejected": final_state["rejected"],
        }


class TrafficShaped(SimTestcase):
    """Ring burst through an HTB-shaped link ("bandwidth_queue"): each
    instance floods ``burst`` messages in ONE tick at a bandwidth of
    ``rate`` msgs/tick and the receiver asserts BOTH properties the
    reference's HTB gives real traffic (``pkg/sidecar/link.go:155-183``):

    - conservation — every message arrives (the admission-cap semantics
      would drop burst − rate of them at send time; rates below one
      message per tick would deliver nothing at all);
    - pacing — the queue services exactly ``rate`` per tick, so message
      j arrives at send_tick + latency + floor(j/rate), and the LAST
      arrival tick is checked exactly (simulated time, no tolerance).
    """

    STATES = ["net-ready"]
    MSG_WIDTH = 1
    IN_MSGS = 4
    MAX_LINK_TICKS = 64  # narrowed by specialize below
    SHAPING = ("latency", "bandwidth_queue")

    @classmethod
    def specialize(cls, groups, tick_ms=1.0):
        from testground_tpu.sim.net import MSG_BYTES

        # one burst/rate per RUN: DEFAULT_LINK (the shaped bandwidth) is
        # global and the outbox shape is a class attribute, so per-group
        # values cannot differ — reject loudly instead of shaping group
        # B at group A's rate
        bursts = {int(g.params.get("burst", 8)) for g in groups} or {8}
        rates = {float(g.params.get("rate", 2.0)) for g in groups} or {2.0}
        if len(bursts) > 1 or len(rates) > 1:
            raise ValueError(
                "traffic-shaped needs identical burst/rate across groups "
                f"(got bursts={sorted(bursts)}, rates={sorted(rates)})"
            )
        burst, rate = bursts.pop(), rates.pop()
        if rate <= 0:
            raise ValueError(
                f"traffic-shaped rate must be > 0 msgs/tick (got {rate}); "
                "rate 0 means an unshaped link — use traffic-allowed"
            )
        # bandwidth bytes/s for `rate` msgs/tick (MSG_BYTES per message)
        bw = rate * MSG_BYTES * 1000.0 / tick_ms
        horizon = int(burst / rate) + 8  # last dt + latency + slack

        class Specialized(cls):
            OUT_MSGS = burst
            # worst case the whole burst lands in one tick (rate ≥ burst)
            IN_MSGS = burst
            MAX_LINK_TICKS = horizon
            DEFAULT_LINK = (1.0, 0.0, bw, 0.0, 0.0, 0.0, 0.0)

        return Specialized

    def init(self, env):
        return {
            "phase": jnp.int32(0),
            "sent_at": jnp.int32(-1),
            "received": jnp.int32(0),
            "last_arrival": jnp.int32(-1),
        }

    def step(self, env, state, inbox, sync, t):
        n = env.test_instance_count
        burst = (
            env.int_param("burst") if "burst" in env.group.params else 8
        )
        rate = (
            env.float_param("rate") if "rate" in env.group.params else 2.0
        )
        succ = jnp.mod(env.global_seq + 1, n)

        phase = state["phase"]
        ready = sync.counts[self.state_id("net-ready")] >= n
        p0 = phase == 0
        send = (phase == 1) & ready

        received = state["received"] + inbox.count
        last_arrival = jnp.where(
            inbox.count > 0, t, state["last_arrival"]
        )
        sent_at = jnp.where(send, t, state["sent_at"])

        # exact HTB schedule: burst message j departs floor(j/rate) ticks
        # late and rides the 1-tick latency floor
        expected_last = sent_at + 1 + jnp.int32(
            jnp.floor((burst - 1) / rate + 1e-4)
        )
        deadline = expected_last + 4
        judge = (phase == 2) & (t > deadline)
        ok = (received == burst) & (last_arrival == expected_last)
        status = jnp.where(
            judge, jnp.where(ok, SUCCESS, FAILURE), RUNNING
        ).astype(jnp.int32)

        ob = Outbox(
            dst=jnp.full((burst,), succ, jnp.int32),
            payload=jnp.ones((burst, 1), jnp.int32),
            valid=jnp.full((burst,), send, bool),
        )
        return self.out(
            {
                "phase": jnp.where(p0, 1, jnp.where(send, 2, phase)).astype(
                    jnp.int32
                ),
                "sent_at": sent_at,
                "received": received,
                "last_arrival": last_arrival,
            },
            status=status,
            outbox=ob,
            signals=self.signal("net-ready") * p0,
        )

    def collect_metrics(self, group, final_state, status):
        return {
            "traffic.received": final_state["received"],
            "traffic.last_arrival_tick": final_state["last_arrival"],
        }


sim_testcases = {
    "ping-pong": PingPong,
    "pingpong-sustained": PingPongSustained,
    "traffic-allowed": TrafficAllowed,
    "traffic-blocked": TrafficBlocked,
    "traffic-shaped": TrafficShaped,
    "traffic-ruled": TrafficRuled,
}
