"""network plan, exec edition: REAL TCP sockets between real processes.

The real-process twin of ``plans/network`` ping-pong (reference
``pingpong.go``): pairs discover each other through the sync service
(address exchange via Publish/Subscribe — the reference's peer-routing
pattern), open a real TCP connection, exchange ping/pong rounds, and
measure RTTs. Like the reference's ``local:exec`` runner, there is no
kernel link shaping here (``TestSidecar=false``, ``local_exec.go:89``) —
shaped-latency assertions are the sim edition's job; this edition proves
the SDK's data-plane path end to end: listener sockets, sync-service
address exchange, and real traffic between OS processes (BASELINE
config 1: network ping-pong, 2 instances, local:exec).
"""

import socket
import time

from testground_tpu.sdk import invoke_map

ROUNDS = 2
BARRIER_TIMEOUT = 60.0  # a crashed peer must fail us, not hang us


def _pair_of(seq: int) -> int:
    """1-based pairing: (1,2), (3,4), ... — 0 means no partner (odd N)."""
    partner = seq + 1 if seq % 2 == 1 else seq - 1
    return partner


def _recv_exact(conn: socket.socket, k: int) -> bytes:
    """TCP is a stream: loop until exactly ``k`` bytes (or EOF)."""
    buf = b""
    while len(buf) < k:
        chunk = conn.recv(k - len(buf))
        if not chunk:
            return buf
        buf += chunk
    return buf


def ping_pong(runenv, initctx):
    client = initctx.sync_client
    n = runenv.test_instance_count
    seq = client.signal_entry("enrolled")
    partner = _pair_of(seq)
    if partner > n:
        runenv.record_message("odd instance count: %d runs solo", seq)
        return None

    # listener first, then publish the address and wait for everyone —
    # no dial can happen before every listener is up
    lis = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lis.bind(("127.0.0.1", 0))
    lis.listen(1)
    lis.settimeout(30.0)
    port = lis.getsockname()[1]
    client.publish("addrs", {"seq": seq, "port": port})
    dialer = seq < partner
    if dialer:  # only the dialer needs the address map
        partner_port = None
        for entry in client.subscribe("addrs", timeout=30.0):
            if int(entry["seq"]) == partner:
                partner_port = int(entry["port"])
                break
        if partner_port is None:
            return f"partner {partner} never published an address"
    client.signal_and_wait(
        "listening", n - (n % 2), timeout=BARRIER_TIMEOUT
    )  # solo skips this barrier

    if dialer:
        conn = socket.create_connection(
            ("127.0.0.1", partner_port), timeout=30.0
        )
    else:
        conn, _ = lis.accept()
    conn.settimeout(30.0)

    try:
        for rnd in range(1, ROUNDS + 1):
            if dialer:
                t0 = time.monotonic()
                conn.sendall(b"ping%d" % rnd)
                got = _recv_exact(conn, 5)
                rtt_ms = (time.monotonic() - t0) * 1000.0
                if got != b"pong%d" % rnd:
                    return f"round {rnd}: expected pong, got {got!r}"
                runenv.R().record_point(f"rtt_round{rnd}_ms", rtt_ms)
                runenv.record_message(
                    "round %d rtt: %.3f ms", rnd, rtt_ms
                )
            else:
                got = _recv_exact(conn, 5)
                if got != b"ping%d" % rnd:
                    return f"round {rnd}: expected ping, got {got!r}"
                conn.sendall(b"pong%d" % rnd)
        # both sides confirm completion before sockets drop
        client.signal_and_wait(
            "done", n - (n % 2), timeout=BARRIER_TIMEOUT
        )
    finally:
        conn.close()
        lis.close()
    return None


def _sim_only(case: str):
    def run(runenv, initctx):
        return (
            f"testcase {case!r} has no exec edition — run it on the "
            "sim:jax runner (its link shaping needs the simulated "
            "transport)"
        )

    return run


if __name__ == "__main__":
    invoke_map(
        {
            "ping-pong": ping_pong,
            # manifest-advertised cases without a real-process edition
            # fail cleanly with a pointer instead of exiting 2
            "traffic-allowed": _sim_only("traffic-allowed"),
            "traffic-blocked": _sim_only("traffic-blocked"),
            "traffic-shaped": _sim_only("traffic-shaped"),
            "pingpong-sustained": _sim_only("pingpong-sustained"),
        }
    )
