"""example: demonstrates the SDK surface.

Port of the reference's ``plans/example`` testcases (output / failure /
panic / params / sync / metrics / artifact — ``plans/example/main.go:11-19``).
"""

import os
import random
import time

from testground_tpu.sdk import invoke_map


def output(runenv, initctx):
    """(``plans/example/output.go``)."""
    runenv.record_message("Hello, World.")
    runenv.record_message(
        "Additional arguments: %d", len(runenv.test_instance_params)
    )
    runenv.R().record_point("donkeypower", 3.0)


def failure(runenv, initctx):
    """(``plans/example/failure.go``)."""
    runenv.record_message("This is what happens when there is a failure")
    return "intentional oops"


def panic(runenv, initctx):
    """(``plans/example/panic.go``)."""
    runenv.record_message("About to hit an unhandled error")
    raise RuntimeError("intentional panic")


def params(runenv, initctx):
    """(``plans/example/params.go``)."""
    runenv.record_message("Params are defined in toml manifest")
    for k, v in runenv.test_instance_params.items():
        runenv.record_message("key: %s, value: %s", k, v)
    runenv.record_message(
        "The value of param2 is %s", runenv.string_param("param2")
    )


def sync(runenv, initctx):
    """Leader/follower release via signal + barrier
    (``plans/example/sync.go``): first to signal 'enrolled' leads; it waits
    for all followers on 'ready', then signals 'released'."""
    client = initctx.sync_client
    seq = client.signal_entry("enrolled")
    runenv.record_message("my sequence ID: %d", seq)

    if seq == 1:
        runenv.record_message("i'm the leader.")
        num_followers = runenv.test_instance_count - 1
        runenv.record_message(
            "waiting for %d instances to become ready", num_followers
        )
        client.barrier("ready", num_followers)
        runenv.record_message("the followers are all ready")
        client.signal_entry("released")
        return None

    sleep = random.random() * 0.5
    runenv.record_message("i'm a follower; signalling ready after %f", sleep)
    time.sleep(sleep)
    client.signal_entry("ready")
    client.barrier("released", 1)
    runenv.record_message("i have been released")


def metrics(runenv, initctx):
    """(``plans/example/metrics.go``, shortened from 30s to stay test-fast)."""
    counter = runenv.R().counter("example.counter1")
    histogram = runenv.R().histogram("example.histogram1")
    gauge = runenv.R().gauge("example.gauge1")
    for _ in range(20):
        data = random.randint(0, 14)
        runenv.record_message("Doing work: %d", data)
        counter.inc(data)
        histogram.update(float(data))
        gauge.update(float(data))
        time.sleep(0.01)


def artifact(runenv, initctx):
    """(``plans/example/artifact.go``): reads a file shipped with the build
    artifact."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "artifact.txt")
    try:
        with open(path) as f:
            runenv.record_message(f.read().strip())
    except OSError as e:
        runenv.record_failure(e)
        return str(e)


if __name__ == "__main__":
    invoke_map(
        {
            "output": output,
            "failure": failure,
            "panic": panic,
            "params": params,
            "sync": sync,
            "metrics": metrics,
            "artifact": artifact,
        }
    )
