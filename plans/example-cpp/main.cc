// example-cpp — a test plan in C++, no SDK bindings.
//
// The compiled-language twin of the reference's plans/example-rust: the
// platform's multi-language property is the instance PROTOCOL, which this
// plan speaks directly —
//   - RunParams from TEST_* environment variables,
//   - lifecycle events as JSON lines on stdout
//     (testground_tpu/sdk/events.py envelope),
//   - coordination via the sync service's newline-JSON TCP protocol
//     (testground_tpu/sync/server.py), keys namespaced "run:<id>:",
//   - the runner's outcome collector fed by publishing the lifecycle
//     event to the run-events topic (sdk/runenv.py _publish_event).
//
// Testcase "sync": leader/follower release — the plans/example sync
// protocol (first "enrolled" signaller leads; it waits for all followers
// on "ready", then signals "released").

#include <arpa/inet.h>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/socket.h>
#include <unistd.h>

namespace {

std::string getenv_or(const char* k, const char* dflt) {
  const char* v = getenv(k);
  return v ? v : dflt;
}

long long now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

void emit(const std::string& event_json) {
  printf("{\"ts\": %lld, \"event\": %s}\n", now_ns(), event_json.c_str());
  fflush(stdout);
}

void emit_message(const std::string& msg) {
  emit("{\"type\": \"message\", \"message\": \"" + msg + "\"}");
}

// One-outstanding-request sync client over the JSON-lines protocol.
class Sync {
 public:
  Sync(const std::string& host, int port, std::string ns)
      : ns_(std::move(ns)) {
    fd_ = socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    inet_pton(AF_INET, host.c_str(), &addr.sin_addr);
    if (connect(fd_, (sockaddr*)&addr, sizeof addr) != 0) {
      perror("sync connect");
      exit(1);
    }
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  }

  long signal_entry(const std::string& state) {
    return call_long("{\"id\": " + next_id() +
                         ", \"op\": \"signal_entry\", \"state\": \"" + ns_ +
                         state + "\"}",
                     "\"seq\":");
  }

  void barrier(const std::string& state, long target) {
    call_long("{\"id\": " + next_id() + ", \"op\": \"barrier\", \"state\": \"" +
                  ns_ + state + "\", \"target\": " + std::to_string(target) +
                  "}",
              "\"ok\":");
  }

  // payload is raw JSON, topic is namespaced by the caller when needed
  long publish_raw(const std::string& topic, const std::string& payload) {
    return call_long("{\"id\": " + next_id() +
                         ", \"op\": \"publish\", \"topic\": \"" + topic +
                         "\", \"payload\": " + payload + "}",
                     "\"seq\":");
  }

  const std::string& ns() const { return ns_; }

 private:
  std::string next_id() { return std::to_string(++id_); }

  // Send one request; read reply lines until the one for this id; return
  // the number after `field` (or 1 for bare "true").
  long call_long(const std::string& req, const std::string& field) {
    std::string data = req + "\n";
    if (send(fd_, data.data(), data.size(), MSG_NOSIGNAL) < 0) exit(1);
    std::string id_pat = "\"id\": " + std::to_string(id_);
    for (;;) {
      size_t nl;
      while ((nl = rbuf_.find('\n')) == std::string::npos) {
        char chunk[4096];
        ssize_t n = recv(fd_, chunk, sizeof chunk, 0);
        if (n <= 0) exit(1);
        rbuf_.append(chunk, (size_t)n);
      }
      std::string line = rbuf_.substr(0, nl);
      rbuf_.erase(0, nl + 1);
      if (line.find(id_pat) == std::string::npos) continue;
      if (line.find("\"error\"") != std::string::npos) {
        fprintf(stderr, "sync error: %s\n", line.c_str());
        exit(1);
      }
      size_t at = line.find(field);
      if (at == std::string::npos) return 1;
      return strtol(line.c_str() + at + field.size(), nullptr, 10);
    }
  }

  int fd_;
  long id_ = 0;
  std::string ns_;
  std::string rbuf_;
};

}  // namespace

int main() {
  std::string run = getenv_or("TEST_RUN", "");
  std::string group = getenv_or("TEST_GROUP_ID", "");
  long count = atol(getenv_or("TEST_INSTANCE_COUNT", "0").c_str());
  long seq_no = atol(getenv_or("TEST_INSTANCE_SEQ", "0").c_str());
  std::string host = getenv_or("SYNC_SERVICE_HOST", "127.0.0.1");
  int port = atoi(getenv_or("SYNC_SERVICE_PORT", "0").c_str());

  emit_message("hello from a C++ test instance");

  Sync sync(host, port, "run:" + run + ":");
  long seq = sync.signal_entry("enrolled");
  emit_message("my sequence ID: " + std::to_string(seq));

  if (seq == 1) {
    emit_message("i'm the leader.");
    sync.barrier("ready", count - 1);
    emit_message("the followers are all ready");
    sync.signal_entry("released");
  } else {
    emit_message("i'm a follower; signalling ready");
    sync.signal_entry("ready");
    sync.barrier("released", 1);
    emit_message("i have been released");
  }

  // lifecycle: stdout event + run-events topic for the outcome collector
  sync.publish_raw(sync.ns() + "__run_events__",
                   "{\"type\": \"success\", \"group\": \"" + group +
                       "\", \"instance\": " + std::to_string(seq_no) +
                       ", \"error\": \"\"}");
  emit("{\"type\": \"success\"}");
  return 0;
}
