#!/bin/sh
# exec:bin build hook (the Dockerfile analog): produce ./run
set -e
g++ -O2 -std=c++17 -o run main.cc
