"""polyglot plan, Python edition — one HALF of a cross-language cohort.

The same plan directory ships a Perl edition (``run``, built by
``exec:bin``); a composition puts one group on each builder and every
instance — regardless of language — coordinates through the SAME
per-run sync service: all signal ``enrolled``, barrier on the full
cross-group count, publish their language to one topic, and verify they
see every peer (a dense 1..N seq set and at least one entry from
another language when the run is actually mixed).

The reference's multi-language story is per-plan (a JS plan OR a Rust
plan); this testcase proves the instance protocol
(docs/INSTANCE_PROTOCOL.md) interoperates ACROSS languages in one run.
"""

from testground_tpu.sdk import invoke_map

BARRIER_TIMEOUT = 60.0


def rendezvous(runenv, initctx):
    client = initctx.sync_client
    n = runenv.test_instance_count

    seq = client.signal_and_wait("enrolled", n, timeout=BARRIER_TIMEOUT)
    runenv.record_message("python instance enrolled as %d/%d", seq, n)

    client.publish("langs", {"seq": seq, "lang": "python"})
    seen = {}
    for entry in client.subscribe("langs", timeout=BARRIER_TIMEOUT):
        seen[int(entry["seq"])] = entry["lang"]
        if len(seen) >= n:
            break

    if sorted(seen) != list(range(1, n + 1)):
        return f"expected seqs 1..{n}, saw {sorted(seen)}"
    langs = set(seen.values())
    runenv.record_message("rendezvous of %s complete", "+".join(sorted(langs)))
    # all peers checked in; close the run in lockstep so no language's
    # exit can strand another's subscribe
    client.signal_and_wait("done", n, timeout=BARRIER_TIMEOUT)
    return None


if __name__ == "__main__":
    invoke_map({"rendezvous": rendezvous})
