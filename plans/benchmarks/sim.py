"""benchmarks plan, sim edition.

Sim twin of the reference's ``plans/benchmarks`` (``benchmarks.go``): the
framework-limits workloads. The reference measures wall-clock seconds for
barriers/pubsub against Redis at up to 50k instances; here the same shapes
measure the simulator's throughput on the device mesh. ``pingpong-flood``
is the headline BASELINE.md workload: every instance sustains shaped
round-trip traffic for a fixed simulated duration (the vectorized analog of
``plans/network`` ping-pong, run at 100k instances).
"""

import jax.numpy as jnp

from testground_tpu.sim.api import (
    RUNNING,
    SUCCESS,
    Outbox,
    SimTestcase,
)

PING = 1
PONG = 2


class Barrier(SimTestcase):
    """All instances signal one state and wait for the full count
    (``benchmarks.go:100-146`` barrier testcase, manifest-bounded at 50k).
    Measures ticks-to-release via finished_at."""

    STATES = ["barrier"]
    OUT_MSGS = 1
    IN_MSGS = 1
    MSG_WIDTH = 1
    MAX_LINK_TICKS = 4

    def step(self, env, state, inbox, sync, t):
        n = env.test_instance_count
        released = sync.counts[self.state_id("barrier")] >= n
        return self.out(
            state,
            status=jnp.where(released, SUCCESS, RUNNING),
            signals=self.signal("barrier") * (t == 0),
        )


class PingPongFlood(SimTestcase):
    """Continuous paired ping-pong under link shaping for a fixed simulated
    duration — sustained per-tick message transport at full instance count.

    Tuned with the fast-path knobs: pairwise traffic means exactly one
    sender per receiver per tick, so ``SLOT_MODE="direct"`` (sort-free slot
    assignment) is valid, provenance is unused (``TRACK_SRC=False``), and
    the calendar horizon only needs to cover the shaped latency.
    """

    MSG_WIDTH = 2
    OUT_MSGS = 1
    IN_MSGS = 1
    MAX_LINK_TICKS = 8
    TRACK_SRC = False
    SLOT_MODE = "direct"
    SHAPING = ("latency",)

    def init(self, env):
        return {"rounds": jnp.int32(0)}

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        duration = (
            env.int_param("duration_ticks")
            if "duration_ticks" in env.group.params
            else 1000
        )
        lat = (
            env.float_param("latency_ms")
            if "latency_ms" in env.group.params
            else 4.0
        )
        partner = env.global_seq ^ 1

        kind = inbox.payload[0]
        got_ping = jnp.any(inbox.valid & (kind == PING))
        got_pong = jnp.any(inbox.valid & (kind == PONG))

        rounds = state["rounds"] + got_pong.astype(jnp.int32)
        # t==0: open with a ping; then reply pong to pings, new ping on pongs
        send = (t == 0) | got_ping | got_pong
        out_kind = jnp.where(got_ping, PONG, PING).astype(jnp.int32)

        done = t >= duration
        return self.out(
            {"rounds": rounds},
            status=jnp.where(done, SUCCESS, RUNNING),
            outbox=Outbox.single(
                partner,
                jnp.stack([out_kind, rounds]),
                send & ~done,
                cls.OUT_MSGS,
                cls.MSG_WIDTH,
            ),
            net_shape=self.link_shape(latency_ms=lat),
            net_shape_valid=t == 0,
        )

    def collect_metrics(self, group, final_state, status):
        return {"flood.rounds": final_state["rounds"]}


class Startup(SimTestcase):
    """time-to-start analog (``benchmarks.go:23``): succeed on the first
    tick; finished_at gives the framework's per-instance startup cost (a
    constant one tick — the containerless win)."""

    def step(self, env, state, inbox, sync, t):
        return self.out(state, status=SUCCESS)


sim_testcases = {
    "barrier": Barrier,
    "pingpong-flood": PingPongFlood,
    "startup": Startup,
}
