"""benchmarks plan, sim edition.

Sim twin of the reference's ``plans/benchmarks`` (``benchmarks.go``): the
framework-limits workloads. The reference measures wall-clock seconds for
barriers/pubsub against Redis at up to 50k instances; here the same shapes
measure the simulator's throughput on the device mesh. ``pingpong-flood``
is the headline BASELINE.md workload: every instance sustains shaped
round-trip traffic for a fixed simulated duration (the vectorized analog of
``plans/network`` ping-pong, run at 100k instances).
"""

import jax
import jax.numpy as jnp

from testground_tpu.sim.api import (
    RUNNING,
    SUCCESS,
    Outbox,
    SimTestcase,
)

PING = 1
PONG = 2


class Barrier(SimTestcase):
    """All instances signal one state and wait for the full count
    (``benchmarks.go:100-146`` barrier testcase, manifest-bounded at 50k).
    Measures ticks-to-release via finished_at."""

    STATES = ["barrier"]
    OUT_MSGS = 1
    IN_MSGS = 1
    MSG_WIDTH = 1
    MAX_LINK_TICKS = 4

    def step(self, env, state, inbox, sync, t):
        n = env.test_instance_count
        released = sync.counts[self.state_id("barrier")] >= n
        return self.out(
            state,
            status=jnp.where(released, SUCCESS, RUNNING),
            signals=self.signal("barrier") * (t == 0),
        )


class PingPongFlood(SimTestcase):
    """Continuous paired ping-pong under link shaping for a fixed simulated
    duration — sustained per-tick message transport at full instance count.

    Tuned with the fast-path knobs: pairwise traffic means exactly one
    sender per receiver per tick, so ``SLOT_MODE="direct"`` (sort-free slot
    assignment) is valid, provenance is unused (``TRACK_SRC=False``), and
    the calendar horizon only needs to cover the shaped latency.
    """

    MSG_WIDTH = 2
    OUT_MSGS = 1
    IN_MSGS = 1
    MAX_LINK_TICKS = 8
    TRACK_SRC = False
    SLOT_MODE = "direct"
    SHAPING = ("latency",)

    def init(self, env):
        return {"rounds": jnp.int32(0)}

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        duration = (
            env.int_param("duration_ticks")
            if "duration_ticks" in env.group.params
            else 1000
        )
        lat = (
            env.float_param("latency_ms")
            if "latency_ms" in env.group.params
            else 4.0
        )
        partner = env.global_seq ^ 1

        kind = inbox.payload[0]
        got_ping = jnp.any(inbox.valid & (kind == PING))
        got_pong = jnp.any(inbox.valid & (kind == PONG))

        rounds = state["rounds"] + got_pong.astype(jnp.int32)
        # t==0: open with a ping; then reply pong to pings, new ping on pongs
        send = (t == 0) | got_ping | got_pong
        out_kind = jnp.where(got_ping, PONG, PING).astype(jnp.int32)

        done = t >= duration
        return self.out(
            {"rounds": rounds},
            status=jnp.where(done, SUCCESS, RUNNING),
            outbox=Outbox.single(
                partner,
                jnp.stack([out_kind, rounds]),
                send & ~done,
                cls.OUT_MSGS,
                cls.MSG_WIDTH,
            ),
            net_shape=self.link_shape(latency_ms=lat),
            net_shape_valid=t == 0,
        )

    def collect_metrics(self, group, final_state, status):
        return {"flood.rounds": final_state["rounds"]}


class Storm(SimTestcase):
    """Gossip-storm flood over a random connection graph — the sim twin of
    ``plans/benchmarks/storm.go:66-120`` (BASELINE config 5 @ 100k).

    Reference protocol: every instance opens listeners, publishes its
    addresses, barriers on "listening", dials ``conn_outgoing`` random
    peers after a random delay, then pushes ``data_size_kb`` KiB down
    each connection in 4 KiB chunks while receivers count bytes read.

    Sim mechanics: the random graph is drawn from each instance's PRNG
    key at init (dials = picking dst indices; the publish/subscribe
    address exchange is unnecessary because instance indices are the
    addresses). Each tick every live connection carries one 4 KiB chunk
    message — multi-message fan-out with Poisson(K) fan-in at the
    receivers, which forces the general "sorted" slot path the flood
    bench avoids. Random per-connection start delays mirror
    ``conn_delay_ms``. Completion: all chunks written → signal
    "done-writing" → barrier on the full count (storm.go's final
    SignalAndWait). The reference's per-dial "outgoing-dials-done"
    barrier (target N·outgoing) collapses to one signal per instance
    when its last connection opens (sync signals are per-tick 0/1).

    Metrics: bytes.sent / bytes.read per instance (storm.go's counters).
    Inbox overflow (fan-in beyond IN_MSGS in one tick) drops chunks like
    a full accept queue; receivers surface it as read<sent totals.
    """

    STATES = ["listening", "dials-done", "done-writing"]
    MSG_WIDTH = 2  # word0: kind, word1: chunk seq
    OUT_MSGS = 8  # upper bound on conn_outgoing
    IN_MSGS = 16  # covers the Poisson(K≤8) per-tick fan-in tail
    MAX_LINK_TICKS = 8
    TRACK_SRC = False
    SHAPING = ("latency",)
    CHUNK_BYTES = 4096  # storm.go buffersize

    def init(self, env):
        cls = type(self)
        n = env.test_instance_count
        k_targets, k_delay = jax.random.split(env.key)
        # conn_outgoing random peers, self-index skipped by shifting
        targets = jax.random.randint(
            k_targets, (cls.OUT_MSGS,), 0, max(n - 1, 1)
        )
        targets = targets + (targets >= env.global_seq)
        delay_max = (
            env.int_param("conn_delay_ticks")
            if "conn_delay_ticks" in env.group.params
            else 32
        )
        delays = jax.random.randint(
            k_delay, (cls.OUT_MSGS,), 0, max(delay_max, 1)
        )
        return {
            "targets": targets.astype(jnp.int32),
            "delays": delays.astype(jnp.int32),
            "sent_chunks": jnp.zeros((cls.OUT_MSGS,), jnp.int32),
            "bytes_read": jnp.int32(0),
            "start": jnp.int32(-1),
            "dialed": jnp.asarray(False),
            "written": jnp.asarray(False),
        }

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        n = env.test_instance_count
        outgoing = min(
            env.int_param("conn_outgoing")
            if "conn_outgoing" in env.group.params
            else 5,
            cls.OUT_MSGS,
        )
        chunks = (
            env.int_param("data_size_kb")
            if "data_size_kb" in env.group.params
            else 128
        ) * 1024 // cls.CHUNK_BYTES

        conn = jnp.arange(cls.OUT_MSGS, dtype=jnp.int32)
        live_conn = conn < outgoing

        listening = sync.counts[self.state_id("listening")] >= n
        start = jnp.where(
            (state["start"] < 0) & listening, t, state["start"]
        )
        started = start >= 0

        # connection c opens at start + delays[c] (conn_delay_ms jitter);
        # writes begin only after the global dials barrier, like the
        # per-connection SignalAndWait("outgoing-dials-done") gate in
        # storm.go — every instance then floods all K connections at once
        opened = started & (t >= start + state["delays"]) & live_conn
        all_dialed = started & jnp.all(
            (t >= start + state["delays"]) | ~live_conn
        )
        sig_dialed = all_dialed & ~state["dialed"]
        writes_open = sync.counts[self.state_id("dials-done")] >= n
        sending = opened & writes_open & (state["sent_chunks"] < chunks)
        sent_chunks = state["sent_chunks"] + sending.astype(jnp.int32)

        all_written = started & jnp.all(
            (sent_chunks >= chunks) | ~live_conn
        )
        sig_written = all_written & ~state["written"]

        kind = inbox.payload[0]
        got = inbox.valid & (kind == PING)  # chunk messages reuse kind=1
        bytes_read = state["bytes_read"] + cls.CHUNK_BYTES * jnp.sum(
            got.astype(jnp.int32)
        )

        done = sync.counts[self.state_id("done-writing")] >= n

        ob = Outbox(
            dst=state["targets"],
            payload=jnp.stack(
                [
                    jnp.full((cls.OUT_MSGS,), PING, jnp.int32),
                    state["sent_chunks"],
                ],
                axis=-1,
            ),
            valid=sending,
        )

        return self.out(
            {
                "targets": state["targets"],
                "delays": state["delays"],
                "sent_chunks": sent_chunks,
                "bytes_read": bytes_read,
                "start": start,
                "dialed": state["dialed"] | sig_dialed,
                "written": state["written"] | sig_written,
            },
            status=jnp.where(done, SUCCESS, RUNNING),
            outbox=ob,
            signals=self.signal("listening") * (t == 0)
            + self.signal("dials-done") * sig_dialed
            + self.signal("done-writing") * sig_written,
        )

    def collect_metrics(self, group, final_state, status):
        cls = type(self)
        return {
            "storm.bytes_sent": cls.CHUNK_BYTES
            * final_state["sent_chunks"].sum(axis=-1),
            "storm.bytes_read": final_state["bytes_read"],
        }


class Startup(SimTestcase):
    """time-to-start analog (``benchmarks.go:23``): succeed on the first
    tick; finished_at gives the framework's per-instance startup cost (a
    constant one tick — the containerless win)."""

    def step(self, env, state, inbox, sync, t):
        return self.out(state, status=SUCCESS)


sim_testcases = {
    "barrier": Barrier,
    "pingpong-flood": PingPongFlood,
    "startup": Startup,
    "storm": Storm,
}
