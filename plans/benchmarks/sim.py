"""benchmarks plan, sim edition.

Sim twin of the reference's ``plans/benchmarks`` (``benchmarks.go``): the
framework-limits workloads. The reference measures wall-clock seconds for
barriers/pubsub against Redis at up to 50k instances; here the same shapes
measure the simulator's throughput on the device mesh. ``pingpong-flood``
is the headline BASELINE.md workload: every instance sustains shaped
round-trip traffic for a fixed simulated duration (the vectorized analog of
``plans/network`` ping-pong, run at 100k instances).
"""

import jax
import jax.numpy as jnp

from testground_tpu.sim.api import (
    FAILURE,
    RUNNING,
    SUCCESS,
    Outbox,
    SimTestcase,
)

PING = 1
PONG = 2

# Barrier percent sweep (``benchmarks.go:109-118``: 0.2 → 1.0 step 0.2).
BARRIER_PCTS = (0.2, 0.4, 0.6, 0.8, 1.0)


class Barrier(SimTestcase):
    """Partial-barrier timing sweep — the sim twin of BarrierBench
    (``benchmarks.go:88-145``, manifest-bounded at 50k instances).

    Per iteration and per percent p ∈ {20,40,60,80,100}: everyone
    signals+waits a full-count "ready" gate, then signals a "test" state
    and waits for ⌊N·p⌋ signallers; the ticks-to-release are the
    ``barrier_time_{p}_percent`` timing metric (simulated ticks stand in
    for the reference's wall-clock seconds against Redis).

    Sync counters are monotone (no reset), so iteration i waits for the
    *cumulative* targets: ready ≥ i·N and test ≥ (i-1)·N + ⌊N·p⌋. All
    instances release on the same global count, so the whole cohort moves
    through the (iteration × percent × {ready,test}) phases in lockstep —
    ``STATES`` holds one ready/test pair per percent and the phase index
    doubles as the state index.
    """

    STATES = [
        s
        for p in BARRIER_PCTS
        for s in (f"ready_{int(p * 100)}", f"test_{int(p * 100)}")
    ]
    OUT_MSGS = 1
    IN_MSGS = 1
    MSG_WIDTH = 1
    MAX_LINK_TICKS = 4

    def init(self, env):
        return {
            "iter": jnp.int32(1),
            "phase": jnp.int32(0),
            "start": jnp.int32(0),
            "sums": jnp.zeros((len(BARRIER_PCTS),), jnp.int32),
        }

    def step(self, env, state, inbox, sync, t):
        n = env.test_instance_count
        n_phases = len(self.STATES)
        iters = (
            env.int_param("barrier_iterations")
            if "barrier_iterations" in env.group.params
            else 10
        )
        # testInstanceNum = max(1, floor(N * percent)) — benchmarks.go:126-130
        test_counts = jnp.asarray(
            [max(1, int(n * p)) for p in BARRIER_PCTS], jnp.int32
        )

        phase, it = state["phase"], state["iter"]
        pct_idx = phase // 2
        is_test = (phase % 2) == 1
        target = jnp.where(
            is_test,
            (it - 1) * n + test_counts[pct_idx],
            it * n,
        )
        released = jnp.take(sync.counts, phase) >= target

        elapsed = t - state["start"]
        sums = state["sums"] + (
            jnp.arange(len(BARRIER_PCTS), dtype=jnp.int32) == pct_idx
        ) * elapsed * (released & is_test)

        nphase_raw = phase + 1
        wrap = nphase_raw >= n_phases
        nphase = jnp.where(wrap, 0, nphase_raw)
        new_phase = jnp.where(released, nphase, phase)
        new_iter = it + (released & wrap)
        done = new_iter > iters
        # entering a test phase starts its timer (barrierTestStart,
        # benchmarks.go:134) — the release propagates via next tick's counts,
        # the sim analog of the reference's Redis round-trip
        start = jnp.where(released & ~is_test, t, state["start"])

        emit = (t == 0) | (released & ~done)
        sig_phase = jnp.where(t == 0, 0, nphase)
        signals = (
            jnp.arange(n_phases, dtype=jnp.int32) == sig_phase
        ).astype(jnp.int32) * emit

        return self.out(
            {"iter": new_iter, "phase": new_phase, "start": start, "sums": sums},
            status=jnp.where(done, SUCCESS, RUNNING),
            signals=signals,
        )

    def collect_metrics(self, group, final_state, status):
        iters = int(group.params.get("barrier_iterations", 10))
        return {
            f"barrier_time_{int(p * 100)}_percent": final_state["sums"][:, i]
            / max(iters, 1)
            for i, p in enumerate(BARRIER_PCTS)
        }


class NetInit(SimTestcase):
    """time-to-network-init (``benchmarks.go:29-48`` NetworkInitBench):
    ticks from start until the network-initialized barrier releases — the
    sim twin of ``MustWaitNetworkInitialized``, whose barrier the sidecars
    signal once per instance (``sidecar_handler.go:40-44``). In the sim
    the link tensors exist from tick 0, so each instance signals on its
    first step and the metric measures the full-count sync round-trip."""

    STATES = ["network-initialized"]
    OUT_MSGS = 1
    IN_MSGS = 1
    MSG_WIDTH = 1
    MAX_LINK_TICKS = 2
    TRACK_SRC = False
    SHAPING = ("latency",)

    def init(self, env):
        return {"init_at": jnp.int32(-1)}

    def step(self, env, state, inbox, sync, t):
        n = env.test_instance_count
        ready = sync.counts[self.state_id("network-initialized")] >= n
        init_at = jnp.where((state["init_at"] < 0) & ready, t, state["init_at"])
        return self.out(
            {"init_at": init_at},
            status=jnp.where(ready, SUCCESS, RUNNING),
            signals=self.signal("network-initialized") * (t == 0),
        )

    def collect_metrics(self, group, final_state, status):
        return {"time_to_network_init_ticks": final_state["init_at"]}


class NetLinkShape(SimTestcase):
    """time-to-shape-network (``benchmarks.go:50-86`` NetworkLinkShapeBench)
    plus an end-to-end verification the shape actually took hold.

    The reference submits a 250 ms-latency config to the sidecar and times
    the config→callback-state round-trip. Here each instance emits the
    shape on tick 0 together with a "network-configured" signal (the
    CallbackState analog — the engine applies egress shapes between ticks
    exactly like the sidecar applies netem between packets); ticks until
    the full-count callback barrier releases are ``time_to_shape_network``.
    Each instance then pings its partner and asserts the observed one-way
    delay equals the shaped latency in ticks — FAILURE on mismatch, so the
    testcase actually exercises the shaping path rather than just timing a
    barrier. With an odd instance count the last instance has no partner
    and succeeds on the callback alone."""

    STATES = ["network-configured"]
    OUT_MSGS = 1
    IN_MSGS = 1
    MSG_WIDTH = 1
    MAX_LINK_TICKS = 256
    TRACK_SRC = False
    SLOT_MODE = "direct"
    SHAPING = ("latency",)

    def init(self, env):
        return {
            "cfg_at": jnp.int32(-1),
            "sent_at": jnp.int32(-1),
            "got_at": jnp.int32(-1),
        }

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        n = env.test_instance_count
        lat = (
            env.float_param("latency_ms")
            if "latency_ms" in env.group.params
            else 250.0
        )
        lat_ticks = min(env.ms_to_ticks(lat), cls.MAX_LINK_TICKS - 1)
        partner = env.global_seq ^ 1
        has_partner = partner < n

        configured = sync.counts[self.state_id("network-configured")] >= n
        just_cfg = (state["cfg_at"] < 0) & configured
        cfg_at = jnp.where(just_cfg, t, state["cfg_at"])

        send = just_cfg & has_partner
        sent_at = jnp.where(send, t, state["sent_at"])
        got = jnp.any(inbox.valid)
        got_at = jnp.where((state["got_at"] < 0) & got, t, state["got_at"])

        delay = got_at - sent_at
        verified = (got_at >= 0) & (delay == lat_ticks)
        wrong = (got_at >= 0) & (delay != lat_ticks)
        ok = jnp.where(has_partner, verified, cfg_at >= 0)

        return self.out(
            {"cfg_at": cfg_at, "sent_at": sent_at, "got_at": got_at},
            status=jnp.where(
                wrong, FAILURE, jnp.where(ok, SUCCESS, RUNNING)
            ),
            outbox=Outbox.single(
                partner, jnp.asarray([PING]), send, cls.OUT_MSGS, cls.MSG_WIDTH
            ),
            signals=self.signal("network-configured") * (t == 0),
            net_shape=self.link_shape(latency_ms=lat),
            net_shape_valid=t == 0,
        )

    def collect_metrics(self, group, final_state, status):
        import numpy as np

        got = np.asarray(final_state["got_at"])
        sent = np.asarray(final_state["sent_at"])
        return {
            "time_to_shape_network_ticks": final_state["cfg_at"],
            "shaped_latency_ticks": np.where(
                (got >= 0) & (sent >= 0), got - sent, np.nan
            ),
        }


# Payload sizes 64 B → 4 KiB by doubling (``benchmarks.go:184``).
SUBTREE_SIZES = (64, 128, 256, 512, 1024, 2048, 4096)


class Subtree(SimTestcase):
    """Pub/sub subtree benchmark (``benchmarks.go:147-276`` SubtreeBench).

    Reference protocol: the first publisher on an "instances" topic
    (seq == 1) becomes THE publisher; it publishes ``iterations`` entries
    per size-series 64B..4KiB, signals "handoff", and subscribers then
    consume every series, verifying each payload, all ending on a
    full-count "end" barrier.

    Sim mechanics: election uses ``SignalEntry`` rank (the same seq==1
    rule); a size-series is a topic whose entries carry
    ``(size ^ iteration, iteration)`` as the payload checksum — a
    subscriber FAILUREs on any mismatch (the reference's "received
    unexpected value"). The publisher streams one entry per tick; after
    "handoff" subscribers drain each topic at SUB_K entries/tick through
    their read cursors. Timing metrics are ticks per series:
    ``subtree_time_{size}_bytes_{publish,receive}_ticks``."""

    STATES = ["elected", "handoff", "end"]
    TOPICS = [f"subtree_{s}" for s in SUBTREE_SIZES]
    OUT_MSGS = 1
    IN_MSGS = 1
    MSG_WIDTH = 1
    PUB_WIDTH = 2
    SUB_K = 8
    TOPIC_CAP = 128
    MAX_LINK_TICKS = 2
    TRACK_SRC = False
    SHAPING = ("latency",)

    def _iters(self, env) -> int:
        iters = (
            env.int_param("subtree_iterations")
            if "subtree_iterations" in env.group.params
            else 64
        )
        if iters > type(self).TOPIC_CAP:
            raise ValueError(
                f"subtree_iterations={iters} exceeds TOPIC_CAP="
                f"{type(self).TOPIC_CAP}; raise the cap or lower iterations"
            )
        return iters

    def init(self, env):
        k = len(SUBTREE_SIZES)
        return {
            "pub_idx": jnp.int32(0),
            "got": jnp.zeros((k,), jnp.int32),
            "bad": jnp.asarray(False),
            "handoff_at": jnp.int32(-1),
            "done_at": jnp.full((k,), -1, jnp.int32),
            "pub_done_at": jnp.full((k,), -1, jnp.int32),
            "sig_handoff": jnp.asarray(False),
            "sig_end": jnp.asarray(False),
        }

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        n = env.test_instance_count
        iters = self._iters(env)
        k = len(SUBTREE_SIZES)
        total = k * iters
        sizes = jnp.asarray(SUBTREE_SIZES, jnp.int32)
        series_ax = jnp.arange(k, dtype=jnp.int32)

        rank = sync.last_seq[self.state_id("elected")]
        is_pub = rank == 1
        is_sub = rank > 1

        # ---------------------------------------------------- publisher path
        can_pub = is_pub & (state["pub_idx"] < total)
        ser = jnp.minimum(state["pub_idx"] // iters, k - 1)
        itr = state["pub_idx"] % iters + 1
        checksum = sizes[ser] ^ itr
        pub_row = series_ax == ser
        pub_valid = pub_row & can_pub
        pub_payload = jnp.where(
            pub_row[:, None],
            jnp.stack([checksum, itr]),
            jnp.zeros((cls.PUB_WIDTH,), jnp.int32),
        )
        pub_idx = state["pub_idx"] + can_pub.astype(jnp.int32)
        pub_done_at = jnp.where(
            pub_row & can_pub & (itr == iters), t, state["pub_done_at"]
        )
        sig_handoff = is_pub & (pub_idx >= total) & ~state["sig_handoff"]
        # the publisher's SignalAndWait(end) — one tick after handoff
        sig_end_pub = is_pub & state["sig_handoff"] & ~state["sig_end"]

        # --------------------------------------------------- subscriber path
        handoff_ok = sync.counts[self.state_id("handoff")] >= 1
        handoff_at = jnp.where(
            (state["handoff_at"] < 0) & handoff_ok & is_sub,
            t,
            state["handoff_at"],
        )
        done_series = state["got"] >= iters
        rser = jnp.minimum(
            jnp.sum(done_series.astype(jnp.int32)), k - 1
        )  # series consumed sequentially; first unfinished
        consuming = is_sub & handoff_ok & ~jnp.all(done_series)
        win_pay = jnp.take(sync.sub_payload, rser, axis=0)  # [K, PW]
        win_val = jnp.take(sync.sub_valid, rser, axis=0)  # [K]
        got_cur = jnp.take(state["got"], rser)
        k_idx = jnp.arange(cls.SUB_K, dtype=jnp.int32)
        take = win_val & (k_idx < iters - got_cur) & consuming
        exp_itr = got_cur + k_idx + 1
        exp_sum = sizes[rser] ^ exp_itr
        mismatch = take & (
            (win_pay[:, 0] != exp_sum) | (win_pay[:, 1] != exp_itr)
        )
        bad = state["bad"] | jnp.any(mismatch)
        ncons = jnp.sum(take.astype(jnp.int32))
        got = state["got"] + (series_ax == rser) * ncons
        newly_done = consuming & (jnp.take(got, rser) >= iters)
        done_at = jnp.where(
            (series_ax == rser) & newly_done, t, state["done_at"]
        )
        sub_consume = (series_ax == rser) * ncons
        sig_end_sub = is_sub & jnp.all(got >= iters) & ~state["sig_end"]

        sig_end = sig_end_pub | sig_end_sub
        end_ok = sync.counts[self.state_id("end")] >= n
        return self.out(
            {
                "pub_idx": pub_idx,
                "got": got,
                "bad": bad,
                "handoff_at": handoff_at,
                "done_at": done_at,
                "pub_done_at": pub_done_at,
                "sig_handoff": state["sig_handoff"] | sig_handoff,
                "sig_end": state["sig_end"] | sig_end,
            },
            status=jnp.where(
                bad, FAILURE, jnp.where(end_ok, SUCCESS, RUNNING)
            ),
            signals=self.signal("elected") * (t == 0)
            + self.signal("handoff") * sig_handoff
            + self.signal("end") * sig_end,
            pub_payload=pub_payload,
            pub_valid=pub_valid,
            sub_consume=sub_consume,
        )

    def collect_metrics(self, group, final_state, status):
        import numpy as np

        iters = int(group.params.get("subtree_iterations", 64))
        done = np.asarray(final_state["done_at"], np.float64)  # [count, k]
        pub_done = np.asarray(final_state["pub_done_at"], np.float64)
        handoff = np.asarray(final_state["handoff_at"], np.float64)
        # per-series elapsed: first series counts from handoff, later ones
        # from the previous series' completion (consumption is sequential)
        prev = np.concatenate([handoff[:, None], done[:, :-1]], axis=1)
        recv = np.where((done >= 0) & (prev >= 0), done - prev, np.nan)
        pub_prev = np.concatenate(
            [np.zeros_like(pub_done[:, :1]), pub_done[:, :-1]], axis=1
        )
        pub = np.where(pub_done >= 0, pub_done - pub_prev, np.nan)
        out = {}
        for i, size in enumerate(SUBTREE_SIZES):
            out[f"subtree_time_{size}_bytes_receive_ticks"] = (
                recv[:, i] / max(iters, 1)
            )
            out[f"subtree_time_{size}_bytes_publish_ticks"] = (
                pub[:, i] / max(iters, 1)
            )
        return out


class PingPongFlood(SimTestcase):
    """Continuous paired ping-pong under link shaping for a fixed simulated
    duration — sustained per-tick message transport at full instance count.

    Tuned with the fast-path knobs: pairwise traffic means exactly one
    sender per receiver per tick, so ``SLOT_MODE="direct"`` (sort-free slot
    assignment) is valid, provenance is unused (``TRACK_SRC=False``), and
    the calendar horizon only needs to cover the shaped latency.
    """

    MSG_WIDTH = 1  # word0 packs kind (low 2 bits) | round << 2
    OUT_MSGS = 1
    IN_MSGS = 1
    MAX_LINK_TICKS = 8
    TRACK_SRC = False
    SLOT_MODE = "direct"
    SHAPING = ("latency",)

    def init(self, env):
        return {"rounds": jnp.int32(0)}

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        duration = (
            env.int_param("duration_ticks")
            if "duration_ticks" in env.group.params
            else 1000
        )
        lat = (
            env.float_param("latency_ms")
            if "latency_ms" in env.group.params
            else 4.0
        )
        partner = env.global_seq ^ 1

        kind = inbox.payload[0] & 3
        got_ping = jnp.any(inbox.valid & (kind == PING))
        got_pong = jnp.any(inbox.valid & (kind == PONG))

        rounds = state["rounds"] + got_pong.astype(jnp.int32)
        # t==0: open with a ping; then reply pong to pings, new ping on pongs
        send = (t == 0) | got_ping | got_pong
        out_kind = jnp.where(got_ping, PONG, PING).astype(jnp.int32)

        done = t >= duration
        return self.out(
            {"rounds": rounds},
            status=jnp.where(done, SUCCESS, RUNNING),
            outbox=Outbox.single(
                partner,
                jnp.stack([out_kind | (rounds << 2)]),
                send & ~done,
                cls.OUT_MSGS,
                cls.MSG_WIDTH,
            ),
            net_shape=self.link_shape(latency_ms=lat),
            net_shape_valid=t == 0,
        )

    def collect_metrics(self, group, final_state, status):
        return {"flood.rounds": final_state["rounds"]}


class Storm(SimTestcase):
    """Gossip-storm flood over a random connection graph — the sim twin of
    ``plans/benchmarks/storm.go:66-120`` (BASELINE config 5 @ 100k).

    Reference protocol: every instance opens listeners, publishes its
    addresses, barriers on "listening", dials ``conn_outgoing`` random
    peers after a random delay, then pushes ``data_size_kb`` KiB down
    each connection in 4 KiB chunks while receivers count bytes read.

    Sim mechanics: the random graph is drawn from each instance's PRNG
    key at init (dials = picking dst indices; the publish/subscribe
    address exchange is unnecessary because instance indices are the
    addresses). Each tick every live connection carries one 4 KiB chunk
    message — multi-message fan-out with Poisson(K) fan-in at the
    receivers, which forces the general "sorted" slot path the flood
    bench avoids. Random per-connection start delays mirror
    ``conn_delay_ms``. Completion: all chunks written → signal
    "done-writing" → barrier on the full count (storm.go's final
    SignalAndWait). The reference's per-dial "outgoing-dials-done"
    barrier (target N·outgoing) collapses to one signal per instance
    when its last connection opens (sync signals are per-tick 0/1).

    Metrics: bytes.sent / bytes.read per instance (storm.go's counters).
    Inbox overflow (fan-in beyond IN_MSGS in one tick) drops chunks like
    a full accept queue; receivers surface it as read<sent totals.
    """

    STATES = ["listening", "dials-done", "done-writing"]
    MSG_WIDTH = 1  # word0 packs kind (low 2 bits) | chunk seq << 2
    OUT_MSGS = 8  # upper bound on conn_outgoing (narrowed per run below)
    IN_MSGS = 16  # covers the Poisson(K) per-tick fan-in tail
    MAX_LINK_TICKS = 8
    TRACK_SRC = False
    SHAPING = ("latency",)
    # every link rides the uniform DEFAULT_LINK latency and is never
    # reshaped, so a calendar bucket only ever fills from one send tick —
    # the transport may skip cross-tick fill tracking (api.py contract)
    CROSS_TICK_STACKING = False
    CHUNK_BYTES = 4096  # storm.go buffersize

    @classmethod
    def specialize(cls, groups, tick_ms=1.0):
        """Size the message axis to the run's actual fan-out instead of
        the manifest upper bound: OUT_MSGS = max conn_outgoing over
        groups. At 100k instances this cuts the per-tick sort + scatter
        index count by OUT_MSGS/8. IN_MSGS stays at the static bound —
        receiver in-degree is Poisson(k) over the whole run (fixed at
        dial time, every live connection floods every tick), so the
        inbox tail must NOT shrink with k or the ~1% of receivers with
        in-degree > 2k would overflow every flooding tick."""
        k = max(
            (
                int(g.params.get("conn_outgoing", 5))
                for g in groups
            ),
            default=5,
        )
        k = max(1, min(k, cls.OUT_MSGS))
        if k == cls.OUT_MSGS:
            return cls
        return type(f"{cls.__name__}_k{k}", (cls,), {"OUT_MSGS": k})

    def init(self, env):
        cls = type(self)
        n = env.test_instance_count
        k_targets, k_delay = jax.random.split(env.key)
        # conn_outgoing random peers, self-index skipped by shifting
        # (jnp.maximum, not python max: n may be a TRACED scalar under
        # shape bucketing — same value either way)
        targets = jax.random.randint(
            k_targets, (cls.OUT_MSGS,), 0, jnp.maximum(n - 1, 1)
        )
        targets = targets + (targets >= env.global_seq)
        delay_max = (
            env.int_param("conn_delay_ticks")
            if "conn_delay_ticks" in env.group.params
            else 32
        )
        delays = jax.random.randint(
            k_delay, (cls.OUT_MSGS,), 0, max(delay_max, 1)
        )
        return {
            "targets": targets.astype(jnp.int32),
            "delays": delays.astype(jnp.int32),
            "sent_chunks": jnp.zeros((cls.OUT_MSGS,), jnp.int32),
            "bytes_read": jnp.int32(0),
            "start": jnp.int32(-1),
            "dialed": jnp.asarray(False),
            "written": jnp.asarray(False),
        }

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        n = env.test_instance_count
        outgoing = min(
            env.int_param("conn_outgoing")
            if "conn_outgoing" in env.group.params
            else 5,
            cls.OUT_MSGS,
        )
        chunks = (
            env.int_param("data_size_kb")
            if "data_size_kb" in env.group.params
            else 128
        ) * 1024 // cls.CHUNK_BYTES

        conn = jnp.arange(cls.OUT_MSGS, dtype=jnp.int32)
        live_conn = conn < outgoing

        listening = sync.counts[self.state_id("listening")] >= n
        start = jnp.where(
            (state["start"] < 0) & listening, t, state["start"]
        )
        started = start >= 0

        # connection c opens at start + delays[c] (conn_delay_ms jitter);
        # writes begin only after the global dials barrier, like the
        # per-connection SignalAndWait("outgoing-dials-done") gate in
        # storm.go — every instance then floods all K connections at once
        opened = started & (t >= start + state["delays"]) & live_conn
        all_dialed = started & jnp.all(
            (t >= start + state["delays"]) | ~live_conn
        )
        sig_dialed = all_dialed & ~state["dialed"]
        writes_open = sync.counts[self.state_id("dials-done")] >= n
        sending = opened & writes_open & (state["sent_chunks"] < chunks)
        sent_chunks = state["sent_chunks"] + sending.astype(jnp.int32)

        all_written = started & jnp.all(
            (sent_chunks >= chunks) | ~live_conn
        )
        sig_written = all_written & ~state["written"]

        kind = inbox.payload[0] & 3
        got = inbox.valid & (kind == PING)  # chunk messages reuse kind=1
        bytes_read = state["bytes_read"] + cls.CHUNK_BYTES * jnp.sum(
            got.astype(jnp.int32)
        )

        done = sync.counts[self.state_id("done-writing")] >= n

        ob = Outbox(
            dst=state["targets"],
            payload=(PING | (state["sent_chunks"] << 2))[:, None],
            valid=sending,
        )

        return self.out(
            {
                "targets": state["targets"],
                "delays": state["delays"],
                "sent_chunks": sent_chunks,
                "bytes_read": bytes_read,
                "start": start,
                "dialed": state["dialed"] | sig_dialed,
                "written": state["written"] | sig_written,
            },
            status=jnp.where(done, SUCCESS, RUNNING),
            outbox=ob,
            signals=self.signal("listening") * (t == 0)
            + self.signal("dials-done") * sig_dialed
            + self.signal("done-writing") * sig_written,
        )

    def collect_metrics(self, group, final_state, status):
        cls = type(self)
        return {
            "storm.bytes_sent": cls.CHUNK_BYTES
            * final_state["sent_chunks"].sum(axis=-1),
            "storm.bytes_read": final_state["bytes_read"],
        }


class Startup(SimTestcase):
    """time-to-start analog (``benchmarks.go:23``): succeed on the first
    tick; finished_at gives the framework's per-instance startup cost (a
    constant one tick — the containerless win)."""

    def step(self, env, state, inbox, sync, t):
        return self.out(state, status=SUCCESS)


sim_testcases = {
    "barrier": Barrier,
    "netinit": NetInit,
    "netlinkshape": NetLinkShape,
    "pingpong-flood": PingPongFlood,
    "startup": Startup,
    "storm": Storm,
    "subtree": Subtree,
}
