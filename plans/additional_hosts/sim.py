"""additional_hosts plan, sim edition.

Sim twin of the reference's ``plans/additional_hosts`` (``main.go:20-40``):
the plan HTTP-GETs a service that is reachable only because the runner
whitelists it as an additional host on the control network
(``pkg/sidecar/docker_reactor.go:69-103`` control routes + the
ADDITIONAL_HOSTS env, ``local_docker.go:141-142``). Here the service is an
echo lane past the instance axis (``SimEnv.hosts``): each instance sends a
request payload to ``env.host_index("http-echo")`` and must get it back
verbatim from the host's lane — the "ok" body check.

``additional_hosts_drop`` proves the *control-route* property: with a
BLACKHOLE filter over every data-plane region, the echo must still answer
— control routes bypass shaping and filters, exactly like the reference's
whitelisted routes survive the sidecar's Drop rules.
"""

import jax.numpy as jnp

from testground_tpu.sim.api import (
    FAILURE,
    FILTER_DROP,
    RUNNING,
    SUCCESS,
    Outbox,
    SimTestcase,
)

REQ = 7  # request marker word


class AdditionalHosts(SimTestcase):
    MSG_WIDTH = 2  # [kind, nonce]
    OUT_MSGS = 1
    IN_MSGS = 4
    MAX_LINK_TICKS = 4
    TRACK_SRC = True
    SHAPING = ("latency", "filters")
    DROP_ALL = False

    def init(self, env):
        return {"bad": jnp.asarray(False)}

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        host = env.host_index("http-echo")  # static; raises if unlisted
        nonce = env.global_seq ^ jnp.int32(0x0BAD5EED)

        # request once the (possible) DROP filter is installed + applied,
        # staggered two senders per tick so the host's IN_MSGS-slot accept
        # queue never overflows at any instance count
        # jnp.maximum, not python max: test_instance_count may be a
        # TRACED scalar under shape bucketing (same value either way)
        window = jnp.maximum(1, -(-env.test_instance_count // 2))
        send = t == 2 + jnp.mod(env.global_seq, window)
        ob = Outbox.single(
            jnp.int32(host),
            jnp.stack([jnp.int32(REQ), nonce]),
            send,
            cls.OUT_MSGS,
            cls.MSG_WIDTH,
        )

        is_echo = (
            inbox.valid
            & (inbox.src == host)
            & (inbox.word(0) == REQ)
            & (inbox.word(1) == nonce)
        )
        # anything else delivered here is a transport violation
        bad = state["bad"] | jnp.any(inbox.valid & ~is_echo)
        got = jnp.any(is_echo)

        drop_filters = jnp.full((len(env.groups),), FILTER_DROP, jnp.int32)
        return self.out(
            {"bad": bad},
            status=jnp.where(
                bad, FAILURE, jnp.where(got, SUCCESS, RUNNING)
            ),
            outbox=ob,
            net_filters=drop_filters if cls.DROP_ALL else None,
            net_filters_valid=(t == 0) if cls.DROP_ALL else False,
        )


class AdditionalHostsDrop(AdditionalHosts):
    """DROP-all data plane; the whitelisted control route still answers."""

    DROP_ALL = True


sim_testcases = {
    "additional_hosts": AdditionalHosts,
    "additional_hosts_drop": AdditionalHostsDrop,
}
