"""splitbrain plan, sim edition.

Sim twin of the reference's ``plans/splitbrain/main.go``: nodes land in
three "regions" by racing a SignalEntry (``main.go:85-88`` — region =
seq % 3), region A then applies a routing filter toward every region-B
node (``main.go:107-130``), and everyone probes everyone. Region C must
reach the whole network; A↔B traffic must fail for the ``drop``/``reject``
testcases and flow for ``accept`` (``expectErrors``, ``main.go:50-59``).

TPU-native mechanics: the region assignment is a dynamic repartition of
the link-filter tensor (``StepOut.region`` reassigns this instance's
partition; ``net_filters`` is per-dst-region — ``sim/net.py``). The HTTP
probe mesh becomes a pipelined probe schedule: at probe step k, instance
i probes peer (i + 1 + k) mod N, so every (receiver, tick) pair sees at
most one probe and one reply — fixed fan-in with no sort pressure.

Beyond the reference, the run ends with a **heal phase**: region A
restores ACCEPT filters and re-probes its nearest region-B peer, proving
the partition is dynamic both ways (the mid-run reconfiguration
semantics of ``pkg/sidecar/sidecar_handler.go:49-82``). The sim's
SignalEntry ordering is the deterministic instance order (cumsum —
``sim/sync_kernel.py``), so seq == global_seq + 1 and peers' regions are
locally computable: region(p) = (p+1) % 3. A region-A instance i has
(i+1) % 3 == 0, hence its nearest B peer ((p+1) % 3 == 1) is exactly
p = i − 2 ≥ 0 — giving the heal sweep fan-in 1.

Outcome accounting (vs ``expectErrors``):
- replies received must equal (N−1) − expected_failures;
- ``reject`` additionally asserts the sender-visible REJECT feedback:
  each region-A instance must see exactly 2·|B| rejected messages (its
  |B| probes + its |B| replies toward B), while ``drop`` must see
  zero — the PROHIBIT-vs-BLACKHOLE distinction of ``link.go:187-217``.
"""

import jax.numpy as jnp

from testground_tpu.sim.api import (
    FAILURE,
    FILTER_ACCEPT,
    FILTER_DROP,
    FILTER_REJECT,
    RUNNING,
    SUCCESS,
    Outbox,
    SimTestcase,
)

PROBE = 1
REPLY = 2

REGION_A = 0
REGION_B = 1
REGION_C = 2

# phases
P_SIGNAL = 0  # t==0: race the region-select signal
P_REGION = 1  # read back seq → region; region A installs filters
P_ROUNDUP = 2  # wait for everyone to be partitioned ("nodeRoundup")
P_PROBE = 3  # pipelined probe sweep
P_JUDGE = 4  # all probes sent + drain window elapsed → verdict
P_HEAL = 5  # region A restores ACCEPT and re-probes a B peer
P_DONE = 6


class _SplitBrain(SimTestcase):
    ACTION = FILTER_ACCEPT  # overridden per testcase

    STATES = ["region-select", "nodeRoundup", "healed"]
    N_REGIONS = 3
    MSG_WIDTH = 2  # word0: kind, word1: probe id
    OUT_MSGS = 2  # slot 0: replies, slot 1: own probes
    IN_MSGS = 4
    MAX_LINK_TICKS = 16
    SHAPING = ("latency", "filters")

    def init(self, env):
        z = jnp.int32(0)
        return {
            "phase": z,
            "region": jnp.int32(-1),
            "k": z,  # next probe index
            "replies": z,  # probe replies received
            "heal_got": jnp.asarray(False),
            "rejected_total": z,
            "deadline": z,
        }

    @staticmethod
    def _region_counts(n):
        # SignalEntry seqs are 1..N; region = seq % 3 (main.go:85-88).
        # n may be a TRACED scalar under shape bucketing (docs/PERF.md
        # "Serving: buckets + packing"), where the exact instance count
        # is runtime data — the traced arm is the closed form of the
        # same count (x in [1, n] with x % 3 == r).
        if isinstance(n, int):
            return [
                sum(1 for x in range(1, n + 1) if x % 3 == r)
                for r in range(3)
            ]
        return [
            n // 3 if r == 0 else jnp.where(n >= r, (n - r) // 3 + 1, 0)
            for r in range(3)
        ]

    def step(self, env, state, inbox, sync, t):
        cls = type(self)
        n = env.test_instance_count
        drain = (
            env.int_param("drain_ticks")
            if "drain_ticks" in env.group.params
            else 8
        )
        counts = self._region_counts(n)
        n_a, n_b = counts[REGION_A], counts[REGION_B]
        phase = state["phase"]
        rejected_total = state["rejected_total"] + sync.rejected

        # --- always answer probes, whatever the phase (the reference's
        # HTTP server serves for the whole test body). The schedule
        # guarantees at most one probe per (receiver, tick).
        kind = inbox.payload[0]
        pid = inbox.payload[1]
        v = inbox.valid
        is_probe = v & (kind == PROBE)
        got_reply = v & (kind == REPLY)
        probe_slot = jnp.argmax(is_probe)
        reply_to = inbox.src[probe_slot]
        reply_id = pid[probe_slot]
        send_reply = jnp.any(is_probe)

        # --- region assignment from the signal race readback
        p_signal = phase == P_SIGNAL
        p_region = phase == P_REGION
        seq = sync.last_seq[self.state_id("region-select")]
        region = jnp.where(
            p_region, jnp.mod(seq, 3), state["region"]
        ).astype(jnp.int32)
        is_a = region == REGION_A

        roundup_done = sync.counts[self.state_id("nodeRoundup")] >= n
        p_roundup = phase == P_ROUNDUP

        # --- probe sweep: at step k probe peer (self + 1 + k) mod n
        p_probe = phase == P_PROBE
        k = state["k"]
        probing = p_probe & (k < n - 1)
        target = jnp.mod(env.global_seq + 1 + k, n)
        replies = state["replies"] + jnp.sum(got_reply.astype(jnp.int32))
        k_next = jnp.where(probing, k + 1, k)
        sweep_done = p_probe & (k >= n - 1)
        deadline = jnp.where(sweep_done, t + drain, state["deadline"])

        # --- verdict (expectErrors, main.go:50-59)
        p_judge = phase == P_JUDGE
        judge = p_judge & (t >= state["deadline"])
        blocked = cls.ACTION != FILTER_ACCEPT
        expected_failures = jnp.where(
            region == REGION_A,
            n_b if blocked else 0,
            jnp.where(region == REGION_B, n_a if blocked else 0, 0),
        )
        replies_ok = replies == (n - 1) - expected_failures
        if cls.ACTION == FILTER_REJECT:
            expected_rejects = jnp.where(is_a, 2 * n_b, 0)
        else:
            expected_rejects = jnp.zeros((), jnp.int32)
        verdict_ok = replies_ok & (rejected_total == expected_rejects)

        # --- heal: region A restores ACCEPT, then probes its nearest B
        # peer (global_seq − 2, see module docstring) until answered;
        # every heal reply received proves that sender's A→B egress is
        # open again. Non-A instances keep serving replies and wait for
        # all |A| heal attestations on the "healed" counter.
        p_heal = phase == P_HEAL
        heal_enter = judge & verdict_ok
        heal_probe = p_heal & is_a & ~state["heal_got"]
        heal_target = jnp.maximum(env.global_seq - 2, 0)
        heal_got = state["heal_got"] | (
            p_heal & is_a & jnp.any(got_reply & (pid == n))
        )
        all_healed = sync.counts[self.state_id("healed")] >= n_a
        finish = p_heal & all_healed & jnp.where(is_a, heal_got, True)

        new_phase = jnp.where(
            p_signal,
            P_REGION,
            jnp.where(
                p_region,
                P_ROUNDUP,
                jnp.where(
                    p_roundup & roundup_done,
                    P_PROBE,
                    jnp.where(
                        sweep_done,
                        P_JUDGE,
                        jnp.where(
                            heal_enter,
                            P_HEAL,
                            jnp.where(finish, P_DONE, phase),
                        ),
                    ),
                ),
            ),
        ).astype(jnp.int32)

        status = jnp.where(
            judge & ~verdict_ok,
            FAILURE,
            jnp.where(finish, SUCCESS, RUNNING),
        ).astype(jnp.int32)

        # --- sends: slot 0 = reply, slot 1 = probe (sweep or heal)
        send_probe = probing | heal_probe
        probe_dst = jnp.where(heal_probe, heal_target, target)
        probe_id = jnp.where(heal_probe, jnp.int32(n), k)
        ob = Outbox.empty(cls.OUT_MSGS, cls.MSG_WIDTH)
        ob = Outbox(
            dst=ob.dst.at[0].set(reply_to).at[1].set(probe_dst),
            payload=ob.payload.at[0, 0]
            .set(REPLY)
            .at[0, 1]
            .set(reply_id)
            .at[1, 0]
            .set(PROBE)
            .at[1, 1]
            .set(probe_id),
            valid=ob.valid.at[0].set(send_reply).at[1].set(send_probe),
        )

        # --- network config: region A applies ACTION toward region B on
        # partition entry, restores ACCEPT on heal entry (both take
        # effect for the next tick's sends — sidecar_handler semantics)
        filters_part = (
            jnp.full((3,), FILTER_ACCEPT, jnp.int32)
            .at[REGION_B]
            .set(cls.ACTION)
        )
        filters_heal = jnp.full((3,), FILTER_ACCEPT, jnp.int32)
        apply_part = p_region & is_a
        apply_heal = heal_enter & is_a

        sig_healed = heal_got & ~state["heal_got"]
        signals = (
            self.signal("region-select") * p_signal
            + self.signal("nodeRoundup") * p_region
            + self.signal("healed") * sig_healed
        )

        return self.out(
            {
                "phase": new_phase,
                "region": region,
                "k": k_next,
                "replies": replies,
                "heal_got": heal_got,
                "rejected_total": rejected_total,
                "deadline": deadline,
            },
            status=status,
            outbox=ob,
            signals=signals,
            net_filters=jnp.where(apply_heal, filters_heal, filters_part),
            net_filters_valid=apply_part | apply_heal,
            region=region,
            region_valid=p_region,
        )

    def collect_metrics(self, group, final_state, status):
        return {
            "splitbrain.region": final_state["region"],
            "splitbrain.replies": final_state["replies"],
            "splitbrain.rejected": final_state["rejected_total"],
        }


class SplitBrainAccept(_SplitBrain):
    ACTION = FILTER_ACCEPT


class SplitBrainReject(_SplitBrain):
    ACTION = FILTER_REJECT


class SplitBrainDrop(_SplitBrain):
    ACTION = FILTER_DROP


sim_testcases = {
    "accept": SplitBrainAccept,
    "reject": SplitBrainReject,
    "drop": SplitBrainDrop,
}
