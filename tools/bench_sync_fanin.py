"""Sync-plane fan-in bench: 100 → 1k → 10k concurrent clients.

The before-picture ROADMAP item 3(a)'s server rewrite will be judged
against: drives a multi-process client ramp against BOTH sync backends
(python ``sync/server.py`` and native ``native/syncsvc.cc``) and banks,
per rung and backend:

- **connect storm**: wall + connects/s to stand up W concurrent
  heartbeat-less clients;
- **signal flood**: W clients each doing K serial ``signal_entry``
  round-trips — per-op p50/p95/p99/max client-observed latency and
  aggregate ops/s;
- **barrier storm**: all W clients ``signal_and_wait`` on one state with
  ``target=W`` — client-observed fan-in latency percentiles, plus the
  server's own armed→release episode wall from the stats plane
  (``barriers.episodes.by_target``, python backend) — the
  "barrier-release latency vs fan-in width" series;
- **pubsub fanout**: S subscribers on one topic, one publisher, M
  entries — delivered frames/s;
- **server-side deltas**: per-op counters + service-time histograms
  from ``sync_stats`` v2 snapshots taken at phase boundaries.

Plus the honesty check the always-on instrumentation owes: an
**instrumented-vs-uninstrumented A/B** at smoke scale (``--no-stats`` /
``--stats 0`` server modes), reported as overhead_pct.

Clients are deliberately NOT the SDK ``SyncClient`` (which spawns
reader+heartbeat threads per connection — 3 × 10k threads of harness
would drown the measurement): each worker multiplexes its client share
in one event loop, one outstanding request per client, latency stamped
send→reply. Since r2 the default worker is the NATIVE mini-client
driver (``native/fanin_driver.cc``, ~1-2 µs/op) — r1 measured the
Python selector workers as the pipeline ceiling on a small box (one
worker alone tops out near 50k round-trips/s, so at 10k clients the
harness, not the server, set flood p50). ``--driver python`` keeps the
old workers for toolchain-less hosts; the bench JSON records which
drove. Per rung the server's own footprint is sampled too (``/proc``):
RSS and open-fd count at every phase boundary, peaks banked in the
JSON — a collapse post-mortem needs resource context, not just
latencies. ``--rungs`` doubles as the laptop escape hatch
(``--rungs 100,1000`` stops the ramp at 1k).

A rung that dies (thread exhaustion, timeouts, refused connects) is a
RESULT, not a crash: the failure mode is recorded in the rung's JSON
and the ramp continues.

Usage::

    python tools/bench_sync_fanin.py                      # full ramp
    python tools/bench_sync_fanin.py --rungs 100,1000 --backends python
    python tools/bench_sync_fanin.py --out BENCH_SYNC_r01.json

Results land as one pretty-printed JSON document (PERF.md "Sync
fan-in" holds the banked round).
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import platform
import selectors
import socket
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

DEFAULT_RUNGS = (100, 1000, 10000)
SIGNAL_OPS = 20  # serial signal_entry round-trips per client
PUB_SUBS = 200  # fanout subscribers (capped to worker 0's share)
PUB_ENTRIES = 50  # entries the publisher appends
CONNECT_BATCH = 200  # in-flight nonblocking connects per worker


# --------------------------------------------------------------- backends


def spawn_backend(backend: str, stats: bool = True):
    """Start a fresh sync server subprocess; returns (proc, (host, port)).
    A fresh server per rung keeps stats deltas and topic state clean."""
    if backend == "python":
        argv = [
            sys.executable,
            "-m",
            "testground_tpu.sync.server",
            "--port",
            "0",
        ]
        if not stats:
            argv.append("--no-stats")
        proc = subprocess.Popen(
            argv,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            cwd=_REPO,
        )
        line = proc.stdout.readline().split()
        # "LISTENING <host> <port>"
        return proc, (line[1], int(line[2]))
    if backend == "native":
        from testground_tpu.native import build_syncsvc, native_available

        if not native_available():
            raise RuntimeError("no C++ toolchain (g++) for the native backend")
        bin_path = build_syncsvc(os.path.join("/tmp", "tg-syncsvc-bench"))
        argv = [bin_path, "--port", "0"]
        if not stats:
            argv += ["--stats", "0"]
        proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True
        )
        line = proc.stdout.readline().split()
        # "LISTENING <port>"
        return proc, ("127.0.0.1", int(line[1]))
    raise ValueError(f"unknown backend {backend!r}")


def raise_nofile() -> int:
    import resource

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
    return resource.getrlimit(resource.RLIMIT_NOFILE)[0]


# ----------------------------------------------------------- mini client


def _send_line(sock: socket.socket, obj: dict) -> None:
    """Small-request send; requests are <200B so a transient full buffer
    is drained with a bounded blocking fallback."""
    data = (json.dumps(obj) + "\n").encode()
    try:
        sock.sendall(data)
    except BlockingIOError:
        sock.setblocking(True)
        sock.settimeout(30)
        sock.sendall(data)
        sock.setblocking(False)


def connect_clients(host, port, n, deadline, errors):
    """Nonblocking batched connect storm; returns connected sockets."""
    sel = selectors.DefaultSelector()
    done: list[socket.socket] = []
    started = 0
    inflight = 0
    while len(done) + len(errors) < n:
        if time.monotonic() > deadline:
            errors.append(f"connect deadline with {len(done)}/{n} up")
            break
        while started < n and inflight < CONNECT_BATCH:
            s = socket.socket()
            s.setblocking(False)
            rc = s.connect_ex((host, port))
            if rc not in (0, 115, 36):  # EINPROGRESS linux/mac
                errors.append(f"connect_ex errno {rc}")
                s.close()
            else:
                sel.register(s, selectors.EVENT_WRITE)
                inflight += 1
            started += 1
        if inflight == 0:
            continue
        for key, _ in sel.select(timeout=1.0):
            s = key.fileobj
            sel.unregister(s)
            inflight -= 1
            err = s.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
            if err:
                errors.append(f"connect SO_ERROR {err}")
                s.close()
            else:
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                done.append(s)
    sel.close()
    return done


def rr_phase(clients, reqs_per_client, build_req, deadline):
    """Serial request/response per client, all clients multiplexed in
    one selector loop. Returns (latencies_ms, errors). ``build_req(i,
    k)`` makes client i's k-th request. A reply line containing
    ``"error"`` counts as an error, not a latency."""
    sel = selectors.DefaultSelector()
    lats: list[float] = []
    errors: list[str] = []
    state = {}  # sock -> [sent_count, t_sent, rbuf, index]
    active = 0
    for i, s in enumerate(clients):
        if reqs_per_client <= 0:
            break
        _send_line(s, build_req(i, 0))
        state[s] = [1, time.perf_counter(), b"", i]
        sel.register(s, selectors.EVENT_READ)
        active += 1
    while active > 0:
        if time.monotonic() > deadline:
            errors.append(f"phase deadline with {active} clients pending")
            break
        for key, _ in sel.select(timeout=1.0):
            s = key.fileobj
            st = state[s]
            try:
                data = s.recv(65536)
            except (BlockingIOError, InterruptedError):
                continue
            except OSError as e:
                errors.append(f"recv: {e}")
                sel.unregister(s)
                active -= 1
                continue
            if not data:
                errors.append("server closed connection")
                sel.unregister(s)
                active -= 1
                continue
            st[2] += data
            while b"\n" in st[2]:
                line, st[2] = st[2].split(b"\n", 1)
                now = time.perf_counter()
                if b'"error"' in line:
                    errors.append(line.decode(errors="replace")[:200])
                else:
                    lats.append((now - st[1]) * 1e3)
                if st[0] < reqs_per_client:
                    _send_line(s, build_req(st[3], st[0]))
                    st[0] += 1
                    st[1] = time.perf_counter()
                else:
                    sel.unregister(s)
                    active -= 1
                    break
    sel.close()
    return lats, errors


def pubsub_phase(clients, n_subs, n_entries, topic, deadline):
    """S subscribers + 1 publisher on ``topic``; returns (wall_secs,
    delivered_frames, errors). Delivery wall runs from the first publish
    to the last subscriber frame."""
    errors: list[str] = []
    if len(clients) < n_subs + 1:
        n_subs = max(0, len(clients) - 1)
    subs = clients[:n_subs]
    if not subs:
        return 0.0, 0, ["no clients left for pubsub"]
    pub = clients[n_subs]
    sel = selectors.DefaultSelector()
    counts = {}  # sock -> [frames, rbuf]
    for i, s in enumerate(subs):
        _send_line(s, {"id": 1, "op": "subscribe", "topic": topic})
        counts[s] = [0, b""]
        sel.register(s, selectors.EVENT_READ)
    # publisher: serial publishes (blocking round-trips on its own sock)
    pub.setblocking(True)
    pub.settimeout(max(1.0, deadline - time.monotonic()))
    prf = pub.makefile("rb")
    t0 = time.perf_counter()
    for m in range(n_entries):
        _send_line(pub, {"id": 2, "op": "publish", "topic": topic,
                         "payload": {"m": m}})
        if not prf.readline():
            errors.append("publisher connection closed")
            break
    want = n_entries
    delivered = 0
    while delivered < want * len(subs):
        if time.monotonic() > deadline:
            errors.append(
                f"pubsub deadline: {delivered}/{want * len(subs)} frames"
            )
            break
        for key, _ in sel.select(timeout=1.0):
            s = key.fileobj
            st = counts[s]
            try:
                data = s.recv(262144)
            except OSError as e:
                errors.append(f"sub recv: {e}")
                sel.unregister(s)
                del counts[s]
                continue
            if not data:
                errors.append("sub closed")
                sel.unregister(s)
                del counts[s]
                continue
            st[1] += data
            n = st[1].count(b"\n")
            if n:
                frames = st[1].split(b"\n")
                st[1] = frames[-1]
                got = sum(1 for f in frames[:-1] if b'"entry"' in f)
                st[0] += got
                delivered += got
    wall = time.perf_counter() - t0
    prf.close()
    sel.close()
    return wall, delivered, errors


# --------------------------------------------------------------- workers


def run_worker(wid, host, port, n_clients, total, cfg, barrier, outq):
    """One worker process: its client share through all phases, phase
    starts synchronized with the parent via the shared barrier."""
    res = {"wid": wid, "errors": []}
    clients = []
    try:
        barrier.wait(timeout=cfg["timeout"])
        t0 = time.perf_counter()
        clients = connect_clients(
            host, port, n_clients,
            time.monotonic() + cfg["timeout"], res["errors"],
        )
        res["connect_wall"] = time.perf_counter() - t0
        res["connected"] = len(clients)
        barrier.wait(timeout=cfg["timeout"])  # connect done

        barrier.wait(timeout=cfg["timeout"])  # flood go
        t0 = time.perf_counter()
        lats, errs = rr_phase(
            clients,
            cfg["signal_ops"],
            lambda i, k: {
                "id": k + 1,
                "op": "signal_entry",
                "state": f"flood-{wid}-{i % 16}",
            },
            time.monotonic() + cfg["timeout"],
        )
        res["flood_wall"] = time.perf_counter() - t0
        res["flood_lats"] = lats
        res["errors"] += errs
        barrier.wait(timeout=cfg["timeout"])  # flood done

        barrier.wait(timeout=cfg["timeout"])  # storm go
        lats, errs = rr_phase(
            clients,
            1,
            lambda i, k: {
                "id": 1,
                "op": "signal_and_wait",
                "state": "storm",
                "target": total,
                "timeout": cfg["timeout"],
            },
            time.monotonic() + cfg["timeout"],
        )
        res["storm_lats"] = lats
        res["errors"] += errs
        barrier.wait(timeout=cfg["timeout"])  # storm done

        barrier.wait(timeout=cfg["timeout"])  # pubsub go (worker 0 only)
        if wid == 0 and clients:
            wall, delivered, errs = pubsub_phase(
                clients,
                min(cfg["pub_subs"], max(1, len(clients) - 1)),
                cfg["pub_entries"],
                "fanout",
                time.monotonic() + cfg["timeout"],
            )
            res["pubsub"] = {"wall_secs": wall, "delivered": delivered}
            res["errors"] += errs
        barrier.wait(timeout=cfg["timeout"])  # pubsub done
    except Exception as e:  # noqa: BLE001 — a dead worker is a result
        res["errors"].append(f"worker died: {type(e).__name__}: {e}")
    finally:
        for s in clients:
            try:
                s.close()
            except OSError:
                pass
        outq.put(res)


def _split_share(width: int, procs: int) -> list[int]:
    share = [width // procs] * procs
    for i in range(width % procs):
        share[i] += 1
    return share


class _PyFleet:
    """The fork()ed selector-worker fleet (the r1 harness, kept as the
    toolchain-less fallback): phase starts synchronized with the parent
    via a shared barrier, results gathered once at the end."""

    def __init__(self, host, port, width, procs, cfg):
        ctx = mp.get_context("fork")
        self._tmo = cfg["timeout"]
        self._barrier = ctx.Barrier(procs + 1)
        self._outq = ctx.Queue()
        self.share = _split_share(width, procs)
        self._workers = [
            ctx.Process(
                target=run_worker,
                args=(
                    i, host, port, self.share[i], width, cfg,
                    self._barrier, self._outq,
                ),
                daemon=True,
            )
            for i in range(procs)
        ]
        for w in self._workers:
            w.start()

    def phase(self, name):
        self._barrier.wait(timeout=self._tmo)

    def results(self):
        res = [self._outq.get(timeout=self._tmo) for _ in self._workers]
        for w in self._workers:
            w.join(timeout=10)
        return res

    def salvage(self, rec):
        """Failure path: whatever the dying workers managed to report
        (they write their res on BrokenBarrierError)."""
        time.sleep(2)
        try:
            while True:
                r = self._outq.get_nowait()
                rec["errors"] += [
                    f"w{r.get('wid')}: {e}" for e in r.get("errors", ())
                ][:5]
                if "connected" in r:
                    rec.setdefault("connected_at_failure", 0)
                    rec["connected_at_failure"] += r["connected"]
        except Exception:  # noqa: BLE001 — queue drained (or unusable)
            pass

    def terminate(self):
        for w in self._workers:
            if w.is_alive():
                w.terminate()


class _DriverFleet:
    """The native mini-client fleet (default when a toolchain exists):
    one ``tg-fanin-driver`` process per worker, "go" per phase on stdin,
    one JSON record per phase on stdout (native/fanin_driver.cc)."""

    def __init__(self, host, port, width, procs, cfg, driver_bin):
        import queue as _queue
        import threading

        self._queue_mod = _queue
        self._tmo = cfg["timeout"]
        self.share = _split_share(width, procs)
        self._records = {i: {} for i in range(procs)}
        self._q: _queue.Queue = _queue.Queue()
        self._procs = []
        for wid in range(procs):
            pub_subs = (
                min(cfg["pub_subs"], max(1, self.share[0] - 1))
                if wid == 0
                else 0
            )
            argv = [
                driver_bin,
                "--host", host, "--port", str(port),
                "--wid", str(wid),
                "--clients", str(self.share[wid]),
                "--total", str(width),
                "--signal-ops", str(cfg["signal_ops"]),
                "--pub-subs", str(pub_subs),
                "--pub-entries", str(cfg["pub_entries"]),
                "--timeout", str(cfg["timeout"]),
            ]
            p = subprocess.Popen(
                argv,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL,
                text=True,
            )
            self._procs.append(p)
            threading.Thread(
                target=self._read_loop, args=(wid, p), daemon=True
            ).start()

    def _read_loop(self, wid, p):
        for line in p.stdout:
            try:
                self._q.put((wid, json.loads(line)))
            except json.JSONDecodeError:
                pass
        self._q.put((wid, None))  # EOF marker

    def phase(self, name):
        if name.endswith("go"):
            for p in self._procs:
                try:
                    p.stdin.write("go\n")
                    p.stdin.flush()
                except (BrokenPipeError, OSError):
                    pass  # a dead driver surfaces at the "done" collect
            return
        # "<phase> done": collect one record per driver within deadline
        want = name.split()[0]
        deadline = time.monotonic() + self._tmo
        got = 0
        while got < len(self._procs):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"{len(self._procs) - got} driver(s) never finished "
                    f"phase {want!r}"
                )
            try:
                wid, msg = self._q.get(timeout=min(remaining, 1.0))
            except self._queue_mod.Empty:
                continue
            if msg is None:
                if want in self._records[wid]:
                    continue  # clean exit after its final record
                raise RuntimeError(f"driver w{wid} died in phase {want!r}")
            self._records[wid][msg.get("phase", want)] = msg
            got += 1

    def results(self):
        out = []
        for wid, recs in self._records.items():
            res = {"wid": wid, "errors": []}
            for r in recs.values():
                res["errors"] += list(r.get("errors", ()))
            if "connect" in recs:
                res["connected"] = recs["connect"].get("connected", 0)
                res["connect_wall"] = recs["connect"].get("wall", 0.0)
            if "flood" in recs:
                res["flood_wall"] = recs["flood"].get("wall", 0.0)
                res["flood_lats"] = recs["flood"].get("lats_ms", [])
            if "storm" in recs:
                res["storm_lats"] = recs["storm"].get("lats_ms", [])
            ps = recs.get("pubsub")
            if ps and not ps.get("skipped"):
                res["pubsub"] = {
                    "wall_secs": ps.get("wall", 0.0),
                    "delivered": ps.get("delivered", 0),
                }
            out.append(res)
        for p in self._procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        return out

    def salvage(self, rec):
        for wid, recs in self._records.items():
            for r in recs.values():
                rec["errors"] += [
                    f"w{wid}: {e}" for e in r.get("errors", ())
                ][:5]
            if "connect" in recs:
                rec.setdefault("connected_at_failure", 0)
                rec["connected_at_failure"] += recs["connect"].get(
                    "connected", 0
                )

    def terminate(self):
        for p in self._procs:
            if p.poll() is None:
                p.kill()


# --------------------------------------------------- server-side sampling


def _server_resources(pid):
    """One RSS + open-fd sample of the server process (``/proc``); None
    off-Linux or once the process is gone."""
    try:
        rss_kb = 0
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss_kb = int(line.split()[1])
                    break
        return {
            "rss_mb": round(rss_kb / 1024.0, 1),
            "open_fds": len(os.listdir(f"/proc/{pid}/fd")),
        }
    except OSError:
        return None


def percentiles(lats, qs=(0.50, 0.95, 0.99)):
    if not lats:
        return {f"p{int(q * 100)}_ms": None for q in qs} | {"max_ms": None}
    xs = sorted(lats)
    out = {}
    for q in qs:
        idx = min(len(xs) - 1, int(q * len(xs)))
        out[f"p{int(q * 100)}_ms"] = round(xs[idx], 3)
    out["max_ms"] = round(xs[-1], 3)
    return out


def _stats_snap(host, port):
    from testground_tpu.sync.stats import fetch_sync_stats

    try:
        return fetch_sync_stats(host, port, timeout=10.0)
    except (OSError, ValueError) as e:
        return {"error": str(e)}


def _ops_delta(a: dict, b: dict) -> dict:
    ao, bo = a.get("ops") or {}, b.get("ops") or {}
    return {op: bo.get(op, 0) - ao.get(op, 0) for op in bo}


# ------------------------------------------------------------------ rungs


def run_rung(backend, width, procs, cfg, log=print):
    """One (backend, width) cell of the ramp. Returns the rung record;
    a failed rung records its failure mode instead of raising."""
    rec = {"clients": width, "procs": procs, "errors": []}
    rec["driver"] = cfg.get("driver", "python")
    proc = None
    fleet = None
    res_samples = {}
    at = {"phase": "startup"}  # bound before try: spawn can raise
    try:
        proc, (host, port) = spawn_backend(backend)
        if rec["driver"] == "native":
            fleet = _DriverFleet(
                host, port, width, procs, cfg, cfg["driver_bin"]
            )
        else:
            fleet = _PyFleet(host, port, width, procs, cfg)
        share = fleet.share

        def phase(name):
            at["phase"] = name
            fleet.phase(name)

        def sample(point):
            s = _server_resources(proc.pid)
            if s is not None:
                res_samples[point] = s

        sample("startup")
        t_conn = time.perf_counter()
        phase("connect go")
        phase("connect done")
        conn_wall = time.perf_counter() - t_conn
        sample("connect")
        snap0 = _stats_snap(host, port)
        t_flood = time.perf_counter()
        phase("flood go")
        phase("flood done")
        flood_wall = time.perf_counter() - t_flood
        sample("flood")
        snap1 = _stats_snap(host, port)
        t_storm = time.perf_counter()
        phase("storm go")
        phase("storm done")
        storm_wall = time.perf_counter() - t_storm
        sample("storm")
        snap2 = _stats_snap(host, port)
        phase("pubsub go")
        phase("pubsub done")
        sample("pubsub")
        snap3 = _stats_snap(host, port)

        results = fleet.results()

        connected = sum(r.get("connected", 0) for r in results)
        flood_lats = [x for r in results for x in r.get("flood_lats", ())]
        storm_lats = [x for r in results for x in r.get("storm_lats", ())]
        rec["errors"] = [e for r in results for e in r.get("errors", ())][:20]
        rec["connect"] = {
            "connected": connected,
            "wall_secs": round(conn_wall, 3),
            "connects_per_sec": round(connected / conn_wall, 1)
            if conn_wall > 0
            else None,
        }
        rec["signal"] = {
            "ops": len(flood_lats),
            "wall_secs": round(flood_wall, 3),
            "ops_per_sec": round(len(flood_lats) / flood_wall, 1)
            if flood_wall > 0
            else None,
            **percentiles(flood_lats),
        }
        rec["barrier"] = {
            "width": width,
            "completed": len(storm_lats),
            "wall_secs": round(storm_wall, 3),
            **percentiles(storm_lats),
        }
        # the server's own armed→release wall for this storm (python
        # backend richness; the by_target delta between snap1 and snap2)
        ep2 = (
            ((snap2.get("barriers") or {}).get("episodes") or {}).get(
                "by_target"
            )
            or {}
        )
        ep1 = (
            ((snap1.get("barriers") or {}).get("episodes") or {}).get(
                "by_target"
            )
            or {}
        )
        release = {}
        for bucket, r2 in ep2.items():
            n = r2.get("count", 0) - (ep1.get(bucket) or {}).get("count", 0)
            if n > 0:
                release[bucket] = {
                    "episodes": n,
                    "total_ms": round(
                        r2.get("total_ms", 0.0)
                        - (ep1.get(bucket) or {}).get("total_ms", 0.0),
                        3,
                    ),
                    "max_ms": r2.get("max_ms"),
                }
        if release:
            rec["barrier"]["server_release_ms"] = release
        pubsub = next(
            (r["pubsub"] for r in results if "pubsub" in r), None
        )
        if pubsub:
            rec["pubsub"] = {
                "subs": min(cfg["pub_subs"], share[0] - 1),
                "entries": cfg["pub_entries"],
                **pubsub,
                "delivered_per_sec": round(
                    pubsub["delivered"] / pubsub["wall_secs"], 1
                )
                if pubsub["wall_secs"] > 0
                else None,
            }
        rec["server"] = {
            "v": snap3.get("v", 1),
            "ops_total": _ops_delta(snap0, snap3),
            "conns_hwm": (snap3.get("conn") or {}).get("hwm"),
            "waiters_hwm": (snap3.get("hwm") or {}).get("waiters"),
        }
        if res_samples:
            rec["server_resources"] = {
                "rss_mb_peak": max(s["rss_mb"] for s in res_samples.values()),
                "open_fds_peak": max(
                    s["open_fds"] for s in res_samples.values()
                ),
                "samples": res_samples,
            }
        ok = connected >= int(0.99 * width) and len(storm_lats) >= int(
            0.99 * width
        )
        rec["outcome"] = "pass" if ok and not rec["errors"] else (
            "pass-with-errors" if ok else "fail"
        )
    except Exception as e:  # noqa: BLE001 — the rung's failure IS the data
        rec["outcome"] = "fail"
        rec["failure_mode"] = (
            f"{type(e).__name__}: {e} (waiting for phase "
            f"{at['phase']!r}, deadline {cfg['timeout']}s)"
        ).strip()
        # salvage the post-mortem: what the server had absorbed when the
        # rung wedged, and whatever the dying workers managed to report
        if proc is not None and proc.poll() is None:
            snap = _stats_snap(host, port)
            rec["server_at_failure"] = {
                "conns": snap.get("conns"),
                "waiters": snap.get("waiters"),
                "conn": snap.get("conn"),
                "ops": snap.get("ops"),
                "barriers": {
                    k: v
                    for k, v in (snap.get("barriers") or {}).items()
                    if k != "episodes"
                },
                "resources": _server_resources(proc.pid),
                "error": snap.get("error"),
            }
        else:
            rec["server_at_failure"] = {
                "error": f"server process exited rc={proc.returncode}"
                if proc is not None
                else "never started"
            }
        if fleet is not None:
            fleet.salvage(rec)
        rec["errors"] = rec["errors"][:20]
    finally:
        if fleet is not None:
            fleet.terminate()
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
    log(
        f"  {backend} @ {width}: {rec.get('outcome')} "
        f"(connect {rec.get('connect', {}).get('connects_per_sec')}/s, "
        f"signal {rec.get('signal', {}).get('ops_per_sec')}/s, "
        f"barrier p99 {rec.get('barrier', {}).get('p99_ms')}ms)"
    )
    return rec


# --------------------------------------------------------------------- A/B


def run_ab(backend="python", clients=200, reps=3, cfg=None, log=print):
    """Instrumented-vs-uninstrumented A/B at smoke scale: same signal
    flood against a stats-on and a stats-off server, alternating reps,
    best-of each arm (the A/B contract: always-on instrumentation must
    cost < 5% — PERF.md 'Sync fan-in')."""
    cfg = cfg or {"signal_ops": 50, "timeout": 60}
    best = {True: 0.0, False: 0.0}
    for _ in range(reps):
        for stats in (True, False):
            proc, (host, port) = spawn_backend(backend, stats=stats)
            try:
                errs: list[str] = []
                conns = connect_clients(
                    host, port, clients, time.monotonic() + 30, errs
                )
                t0 = time.perf_counter()
                lats, errs2 = rr_phase(
                    conns,
                    cfg["signal_ops"],
                    lambda i, k: {
                        "id": k + 1,
                        "op": "signal_entry",
                        "state": f"ab-{i % 16}",
                    },
                    time.monotonic() + cfg["timeout"],
                )
                wall = time.perf_counter() - t0
                rate = len(lats) / wall if wall > 0 else 0.0
                best[stats] = max(best[stats], rate)
                for s in conns:
                    s.close()
            finally:
                proc.terminate()
                proc.wait(timeout=10)
    on, off = best[True], best[False]
    overhead = (off - on) / off * 100 if off > 0 else None
    rec = {
        "backend": backend,
        "clients": clients,
        "signal_ops": cfg["signal_ops"],
        "reps": reps,
        "instrumented_ops_per_sec": round(on, 1),
        "uninstrumented_ops_per_sec": round(off, 1),
        "overhead_pct": round(overhead, 2) if overhead is not None else None,
    }
    log(
        f"  A/B ({backend}, {clients} clients): instrumented {on:.0f}/s "
        f"vs uninstrumented {off:.0f}/s → overhead "
        f"{rec['overhead_pct']}%"
    )
    return rec


# --------------------------------------------------------------------- main


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--rungs", default=",".join(map(str, DEFAULT_RUNGS)),
        help="comma-separated concurrent-client widths",
    )
    ap.add_argument("--backends", default="python,native")
    ap.add_argument("--procs", type=int, default=0,
                    help="worker processes (0 = auto)")
    ap.add_argument("--signal-ops", type=int, default=SIGNAL_OPS)
    ap.add_argument("--pub-subs", type=int, default=PUB_SUBS)
    ap.add_argument("--pub-entries", type=int, default=PUB_ENTRIES)
    ap.add_argument("--timeout", type=float, default=180.0,
                    help="per-phase deadline seconds")
    ap.add_argument("--driver", choices=("auto", "native", "python"),
                    default="auto",
                    help="mini-client fleet: the native epoll driver "
                    "(default when g++ exists; the harness stops being "
                    "the bottleneck) or the r1 python selector workers")
    ap.add_argument("--no-ab", action="store_true",
                    help="skip the instrumentation A/B")
    ap.add_argument("--out", default="", help="write the JSON document here")
    args = ap.parse_args(argv)

    nofile = raise_nofile()
    rungs = [int(x) for x in args.rungs.split(",") if x]
    backends = [b.strip() for b in args.backends.split(",") if b.strip()]
    cfg = {
        "signal_ops": args.signal_ops,
        "pub_subs": args.pub_subs,
        "pub_entries": args.pub_entries,
        "timeout": args.timeout,
    }
    driver = args.driver
    if driver == "auto":
        from testground_tpu.native import native_available

        driver = "native" if native_available() else "python"
    cfg["driver"] = driver
    if driver == "native":
        from testground_tpu.native import build_fanin_driver

        cfg["driver_bin"] = build_fanin_driver(
            os.path.join("/tmp", "tg-syncsvc-bench")
        )
    doc = {
        "bench": "sync_fanin",
        "rungs": rungs,
        "config": {**{k: v for k, v in cfg.items() if k != "driver_bin"},
                   "nofile": nofile},
        "host": {
            "cpus": os.cpu_count(),
            "platform": platform.platform(),
            "python": platform.python_version(),
        },
        "backends": {},
    }
    for backend in backends:
        doc["backends"][backend] = {}
        print(f"backend {backend}:")
        for width in rungs:
            # ONE native driver epolls the whole fleet (measured faster
            # than splitting: fewer context switches on small boxes);
            # the python workers need the process spread
            if driver == "native":
                procs = args.procs or 1
            else:
                procs = args.procs or max(1, min(8, width // 250 or 1))
            doc["backends"][backend][str(width)] = run_rung(
                backend, width, procs, cfg
            )
    if not args.no_ab:
        print("instrumentation A/B:")
        doc["ab"] = run_ab()
    out = json.dumps(doc, indent=1, sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
        print(f"wrote {args.out}")
    else:
        print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
