"""``make netmap-smoke``: drive the network-topology plane end to end
through a real daemon — a clustered composition (two ping-pong pairs,
four singleton groups, zero cross-cluster traffic) with
``netmatrix = true``, then assert every surface:

1. the journal's ``sim.net_matrix`` block reconciles exactly
   (conservation, cell-wise send identity);
2. ``sim_netmatrix.jsonl`` streams as the ``netmatrix`` family on
   ``GET /stream`` and is fetchable via ``GET /artifact``;
3. ``tg netmap <task>`` (the real CLI against ``--endpoint``) renders
   the heatmap with every group label and an exact conservation line;
4. ``tg netmap <task> --cut 2`` recommends the cluster split — each
   ping-pong pair co-located, the two pairs on different shards;
5. the Prometheus page is a valid exposition and carries the bounded
   ``tg_net_pair_*`` series plus the elision gauge.

Exits non-zero with a readable message on any violation; prints a
one-line summary on success. Self-contained: runs against a temporary
$TESTGROUND_HOME on the CPU backend, so it is safe in CI.
"""

import io
import json
import os
import re
import sys
import tempfile
import time
from contextlib import redirect_stdout

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

GROUPS = ("c0a", "c0b", "c1a", "c1b")  # pairs: (c0a,c0b) and (c1a,c1b)


def fail(msg: str) -> "None":
    print(f"netmap-smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def tg(args) -> tuple[int, str]:
    """Invoke the real CLI entry point, capturing stdout."""
    from testground_tpu.cli.main import main

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = main(args)
    return rc, buf.getvalue()


def main() -> int:
    os.environ["TESTGROUND_HOME"] = tempfile.mkdtemp(prefix="tg-smoke-")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from testground_tpu.client import Client
    from testground_tpu.config import EnvConfig
    from testground_tpu.daemon import Daemon
    from testground_tpu.sim import netmatrix as nm

    daemon = Daemon(env=EnvConfig.load(), listen="localhost:0")
    daemon.start()
    try:
        client = Client(daemon.address)
        client.import_plan(os.path.join(REPO_ROOT, "plans", "network"))
        tid = client.run(
            {
                "metadata": {"name": "netmap-smoke"},
                "global": {
                    "plan": "network",
                    "case": "ping-pong",
                    "builder": "sim:plan",
                    "runner": "sim:jax",
                    "run_config": {
                        "telemetry": True,
                        "netmatrix": True,
                        "chunk": 16,
                        "max_ticks": 512,
                    },
                },
                "groups": [
                    {"id": g, "instances": {"count": 1}} for g in GROUPS
                ],
            }
        )
        deadline = time.time() + 180
        while time.time() < deadline:
            t = client.status(tid)
            if t["states"][-1]["state"] in ("complete", "canceled"):
                break
            time.sleep(0.2)
        else:
            fail(f"task {tid} did not finish")
        if t.get("error"):
            fail(f"run errored: {t['error']}")

        # --- 1. journal block: exact conservation on a clustered run
        sim = client.stats(tid).get("sim") or {}
        block = sim.get("net_matrix")
        if not block:
            fail("journal has no sim.net_matrix block")
        if block["labels"] != list(GROUPS):
            fail(f"labels {block['labels']} != {list(GROUPS)}")
        if block["mismatches"]:
            fail(f"conservation mismatches: {block['mismatches']}")
        mat = np.asarray(block["matrix"], np.int64)
        if block["totals"]["delivered"] != sim.get("msgs_delivered"):
            fail("matrix delivered total != journal msgs_delivered")
        if block["totals"]["delivered"] <= 0:
            fail("clustered run delivered no traffic")
        send_lhs = mat[nm.NM_SENT]
        send_rhs = (
            mat[nm.NM_ENQUEUED]
            + mat[nm.NM_DROPPED]
            + mat[nm.NM_REJECTED]
            + mat[nm.NM_FAULT]
        )
        if not np.array_equal(send_lhs, send_rhs):
            fail("cell-wise send identity does not close")
        # the composition is two isolated pairs: no cross-cluster cells
        cross = mat[:, :2, 2:].sum() + mat[:, 2:, :2].sum()
        if cross != 0:
            fail(f"unexpected cross-cluster traffic ({cross} msgs)")

        # --- 2. the stream family and the artifact route
        rows = [
            r
            for r in client.stream(tid, families=("netmatrix",))
            if r is not None
        ]
        if not rows or {r["stream"] for r in rows} != {"netmatrix"}:
            fail("GET /stream served no netmatrix-family rows")
        if [r["chunk"] for r in rows] != list(range(len(rows))):
            fail("netmatrix rows are not one-per-chunk contiguous")
        back = nm.matrix_from_rows(rows, len(GROUPS))
        if not np.array_equal(back, mat):
            fail("streamed cells do not reconstruct the journal matrix")
        art = client.artifact(tid, "sim_netmatrix.jsonl")
        if len(art.splitlines()) != len(rows):
            fail("GET /artifact sim_netmatrix.jsonl row count mismatch")

        # --- 3. the real CLI: heatmap screen
        rc, screen = tg(["--endpoint", daemon.address, "netmap", tid])
        if rc != 0:
            fail(f"tg netmap exited {rc}")
        for label in GROUPS:
            if label not in screen:
                fail(f"heatmap is missing group {label!r}")
        if "conservation" not in screen:
            fail("heatmap is missing the conservation verdict")
        if "FAILED" in screen:
            fail(f"tg netmap reports failure:\n{screen}")
        rc, out = tg(
            ["--endpoint", daemon.address, "netmap", tid, "--json"]
        )
        if rc != 0:
            fail(f"tg netmap --json exited {rc}")
        if json.loads(out)["totals"] != block["totals"]:
            fail("tg netmap --json totals != journal totals")

        # --- 4. the cut advisor recommends the cluster split
        rc, cut_screen = tg(
            ["--endpoint", daemon.address, "netmap", tid, "--cut", "2"]
        )
        if rc != 0:
            fail(f"tg netmap --cut 2 exited {rc}")
        rec = nm.cut_advisor(nm.matrix_bytes(mat), 2, labels=GROUPS)
        shards = [set(s) for s in rec["shards"]]
        if shards != [{"c0a", "c0b"}, {"c1a", "c1b"}]:
            fail(f"--cut 2 did not recover the clusters: {rec['shards']}")
        if rec["cut"] != 0.0:
            fail(f"cluster split should cut nothing, got {rec['cut']}")
        for pair in ("c0a", "c0b"), ("c1a", "c1b"):
            line = next(
                (
                    ln
                    for ln in cut_screen.splitlines()
                    if pair[0] in ln and pair[1] in ln
                ),
                None,
            )
            if line is None:
                fail(f"--cut 2 screen does not co-locate {pair}")

        # --- 5. Prometheus: valid exposition, bounded pair series
        text = client.metrics()
        series = re.compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [^ ]+$"
        )
        for ln in text.splitlines():
            if ln and not ln.startswith("#") and not series.match(ln):
                fail(f"invalid exposition line: {ln!r}")
        for name in (
            "tg_net_pair_msgs_total{",
            "tg_net_pair_bytes_total{",
            "tg_net_pairs_elided{",
            "tg_net_conservation_mismatches{",
        ):
            if name not in text:
                fail(f"{name.rstrip('{')} missing from /metrics")
        n_pairs = len(
            set(re.findall(r'tg_net_pair_bytes_total\{[^}]*\}', text))
        )
        if not 0 < n_pairs <= 16:
            fail(f"pair-series cardinality {n_pairs} outside (0, 16]")
    finally:
        daemon.stop()

    print(
        f"netmap-smoke: OK — {len(rows)} chunk rows, "
        f"delivered={block['totals']['delivered']} "
        f"cut2={rec['shards']} (cut {rec['cut']:.0f}B) "
        f"pair_series={n_pairs}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
