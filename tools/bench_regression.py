#!/usr/bin/env python
"""Bench regression sentinel: gate the newest banked bench row per key.

Reads the append-only ``BENCH_HISTORY.jsonl`` written by ``bench.py
--bank`` and, for every history key ``(workload, instances, backend,
device_kind, transport)``, compares the newest row's headline value
against the median of the prior rows for that key.  A confident
regression — newest value slower than baseline by more than the
tolerance factor — exits non-zero so CI fails; ``inconclusive`` rows
(no baseline yet, or slower but within the noise bound) are journaled
to stderr and pass.

The default tolerance is deliberately generous (2.5x): bench boxes in
CI are shared and noisy (±40% run-to-run has been observed), so only
an unambiguous slowdown should gate.  Tighten with ``--tolerance`` on
quieter hardware.

Usage:
    python tools/bench_regression.py [--history PATH] [--tolerance X]
                                     [--json]

Exit codes: 0 ok/improved/inconclusive only, 1 at least one confident
regression, 2 usage or unreadable history.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from testground_tpu.analysis.bench_history import (  # noqa: E402
    HISTORY_FILE,
    load_history,
    sentinel_report,
)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--history",
        default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            HISTORY_FILE,
        ),
        help="bench history jsonl (default: repo-root BENCH_HISTORY.jsonl)",
    )
    p.add_argument(
        "--tolerance",
        type=float,
        default=2.5,
        help="regression bound: fail when newest < baseline/tolerance "
        "(default 2.5, i.e. only >2.5x slowdowns gate)",
    )
    p.add_argument("--json", action="store_true", help="emit the report as JSON")
    args = p.parse_args()

    if args.tolerance <= 1.0:
        print("error: --tolerance must be > 1.0", file=sys.stderr)
        return 2

    rows = load_history(args.history)
    if not rows:
        print(f"error: no readable rows in {args.history}", file=sys.stderr)
        return 2

    report = sentinel_report(rows, tolerance=args.tolerance)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for key in report["keys"]:
            label = (
                f"{key['workload']}/{key['instances']} "
                f"{key['backend']}:{key['device_kind']} {key['transport']}"
            )
            if key.get("mesh"):
                label += f" mesh={key['mesh']}"
            line = f"{key['verdict']:<13} {label}  value={key['value']:.1f}"
            if key.get("baseline") is not None:
                line += f"  baseline={key['baseline']:.1f}  x{key['ratio']:.3f}"
            line += f"  ({key['reason']})"
            print(line)
    if report["inconclusive"]:
        print(
            f"# {report['inconclusive']} inconclusive key(s) — journaled, "
            "not gating",
            file=sys.stderr,
        )
    if report["regressions"]:
        print(
            f"error: {report['regressions']} confident regression(s) "
            f"(tolerance {args.tolerance:g}x)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
