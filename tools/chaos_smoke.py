"""``make chaos-smoke``: run the ``plans/chaos`` composition
(``plans/chaos/_compositions/smoke.toml`` — crash-mid-barrier + link
flap + partition-and-heal) on the CPU backend and assert the fault
plane's contract end-to-end:

- the run COMPLETES with every instance SUCCESS (no barrier deadlock:
  the live-degraded barrier released the survivors when the schedule
  crashed instances mid-barrier, and the heal handshake crossed the
  healed partition);
- the journal reports the scheduled chaos exactly (2 crashed, 2
  restarted, nonzero fault-dropped traffic);
- the flow-conservation identity holds exactly under chaos:
  sent = delivered + in-flight + dropped + rejected + fault_dropped;
- the per-tick telemetry rows sum to the journal's cumulative totals,
  fault_dropped included;
- determinism: a second run of the same composition produces the
  identical per-tick counter stream.

Exits non-zero with a readable message on any violation. Self-contained:
temporary $TESTGROUND_HOME, CPU backend — safe in CI (mirrors
``tools/telemetry_smoke.py``).
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def fail(msg: str) -> "None":
    print(f"chaos-smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def _run_once(engine, comp, manifest, sources):
    import time

    from testground_tpu.engine import State

    tid = engine.queue_run(comp, manifest, sources_dir=sources)
    deadline = time.time() + 300
    while time.time() < deadline:
        t = engine.get_task(tid)
        if t is not None and t.state().state in (
            State.COMPLETE,
            State.CANCELED,
        ):
            return t
        time.sleep(0.05)
    fail(f"task {tid} did not finish within 300s")


def _read_rows(env, task):
    from testground_tpu.sim.telemetry import SIM_SERIES_FILE

    path = os.path.join(
        env.dirs.outputs(), "chaos", task.id, SIM_SERIES_FILE
    )
    if not os.path.isfile(path):
        fail(f"{SIM_SERIES_FILE} was not written ({path})")
    rows = []
    with open(path) as f:
        for i, line in enumerate(f):
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError as e:
                fail(f"line {i + 1} is not JSON: {e}")
    if not rows:
        fail(f"{SIM_SERIES_FILE} is empty")
    return rows


def main() -> int:
    os.environ["TESTGROUND_HOME"] = tempfile.mkdtemp(prefix="tg-chaos-")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from testground_tpu.api import TestPlanManifest, load_composition
    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.config import EnvConfig
    from testground_tpu.engine import Engine, EngineConfig, Outcome
    from testground_tpu.sim.runner import SimJaxRunner
    from testground_tpu.sim.telemetry import telemetry_totals

    plan_dir = os.path.join(REPO_ROOT, "plans", "chaos")
    comp_path = os.path.join(plan_dir, "_compositions", "smoke.toml")
    manifest = TestPlanManifest.load_file(
        os.path.join(plan_dir, "manifest.toml")
    )

    env = EnvConfig.load()
    engine = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    engine.start_workers()
    try:
        tasks = [
            _run_once(engine, load_composition(comp_path), manifest, plan_dir)
            for _ in range(2)  # second run pins determinism
        ]
    finally:
        engine.stop()

    task = tasks[0]
    if task.outcome() != Outcome.SUCCESS:
        fail(
            f"run outcome {task.outcome().value}: {task.error} — the "
            "chaos run must COMPLETE (live-degraded barrier + healed "
            "partition), not deadlock or fail"
        )
    sim = task.result["journal"]["sim"]

    # scheduled chaos happened, and exactly as declared
    if sim.get("faults_crashed") != 2:
        fail(f"faults_crashed = {sim.get('faults_crashed')} != 2")
    if sim.get("faults_restarted") != 2:
        fail(f"faults_restarted = {sim.get('faults_restarted')} != 2")
    if not sim.get("msgs_fault_dropped", 0) > 0:
        fail("msgs_fault_dropped is 0 — the flap/partition windows and "
             "dead-target kills produced no counted drops")

    # chaos flow conservation, exact
    lhs = sim["msgs_sent"]
    rhs = (
        sim["msgs_delivered"]
        + sim["msgs_in_flight"]
        + sim["msgs_dropped"]
        + sim["msgs_rejected"]
        + sim["msgs_fault_dropped"]
    )
    if lhs != rhs:
        fail(
            f"conservation violated: sent {lhs} != delivered "
            f"{sim['msgs_delivered']} + in-flight {sim['msgs_in_flight']} "
            f"+ dropped {sim['msgs_dropped']} + rejected "
            f"{sim['msgs_rejected']} + fault_dropped "
            f"{sim['msgs_fault_dropped']} = {rhs}"
        )

    # per-tick rows sum back to the cumulative journal totals
    rows = _read_rows(env, task)
    for col, got in telemetry_totals(rows).items():
        want = sim[f"msgs_{col}"]
        if got != want:
            fail(f"Σ {col} = {got} != journal msgs_{col} = {want}")

    # determinism: same composition (same seed + schedule) → identical
    # per-tick counter streams
    rows2 = _read_rows(env, tasks[1])
    strip = lambda rs: [  # noqa: E731
        {k: v for k, v in r.items() if k != "run"} for r in rs
    ]
    if strip(rows) != strip(rows2):
        fail("two runs of the same seed + schedule diverged — the fault "
             "plane broke determinism")

    print(
        "chaos-smoke: OK — crashed={c} restarted={r} fault_dropped={d} "
        "of sent={s}, conservation exact, {n} per-tick rows "
        "deterministic".format(
            c=sim["faults_crashed"],
            r=sim["faults_restarted"],
            d=sim["msgs_fault_dropped"],
            s=sim["msgs_sent"],
            n=len(rows),
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
