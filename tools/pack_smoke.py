"""``make pack-smoke``: the multi-tenant serving fast path's end-to-end
contract (PERF.md "Serving: buckets + packing") on the CPU backend — the
ROADMAP item-2 soak proof at test scale:

1. **Warm the bucket ladder** — one ``sim:plan`` precompile with
   ``build_buckets = true`` compiles the canonical bucket programs into
   the persistent cache (per-bucket compile_secs in the build marker).
2. **Isolated baseline** — one small bucketed run alone (``pack=false``)
   for the single-run wall-clock rate.
3. **The soak** — N=8 concurrent small ``tg run``s at DIFFERENT
   instance counts, all ``bucket=auto pack=true``, queued against one
   engine. Asserts:
   - **zero cold compiles**: every run journals
     ``sim.bucket.compile_cache == "hit"`` (jax's own cache_hits
     monitoring events — the `tg_compile_bucket_hit` counter's source);
   - **packed execution**: at least 7 of the 8 runs share one vmapped
     device program (``sim.pack.members >= 7`` — one worker claims the
     queue; the other worker may grab one run solo);
   - **exact-N results**: every run reports its own instance count's
     outcomes (all-success at its exact N, not the bucket size);
   - **amortization**: aggregate peer·ticks/s across the batch
     > N/2 × the isolated single-run rate (one dispatch per chunk for
     the whole pack vs one per run).

Exits non-zero with a readable message on any violation. Self-contained:
temporary $TESTGROUND_HOME, CPU backend — safe in CI (mirrors
``tools/slo_smoke.py``).
"""

import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

LADDER = "32,64"
RUN_CFG = {
    "bucket": "auto",
    "bucket_ladder": LADDER,
    "telemetry": True,
    "max_ticks": 2048,
    "chunk": 32,
}
# eight tenants, eight different sizes, one bucket (32)
TENANT_SIZES = (5, 9, 13, 17, 21, 25, 29, 24)


def fail(msg: str) -> None:
    print(f"pack-smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def _comp(n: int, seed: int, pack: bool):
    from testground_tpu.api import (
        Composition,
        Global,
        Group,
        Instances,
        generate_default_run,
    )

    return generate_default_run(
        Composition(
            global_=Global(
                plan="network",
                case="ping-pong",
                builder="sim:plan",
                runner="sim:jax",
                run_config={**RUN_CFG, "pack": pack, "seed": seed},
            ),
            groups=[Group(id="all", instances=Instances(count=n))],
        )
    )


def _wait(engine, tids, budget=600):
    from testground_tpu.engine import State

    deadline = time.time() + budget
    while time.time() < deadline:
        done = [
            engine.get_task(t).state().state
            in (State.COMPLETE, State.CANCELED)
            for t in tids
        ]
        if all(done):
            return [engine.get_task(t) for t in tids]
        time.sleep(0.2)
    fail(f"tasks did not finish within {budget}s")


def main() -> int:
    home = tempfile.mkdtemp(prefix="tg-pack-smoke-")
    os.environ["TESTGROUND_HOME"] = home
    os.makedirs(os.path.join(home, "plans"), exist_ok=True)
    shutil.copytree(
        os.path.join(REPO_ROOT, "plans", "network"),
        os.path.join(home, "plans", "network"),
    )
    sources = os.path.join(home, "plans", "network")

    from testground_tpu.api import TestPlanManifest
    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.config import EnvConfig
    from testground_tpu.engine import Engine, EngineConfig, Outcome
    from testground_tpu.sim.runner import SimJaxRunner

    env = EnvConfig.load()
    engine = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    manifest = TestPlanManifest.load_file(
        os.path.join(sources, "manifest.toml")
    )

    # ---- 1. warm the ladder (tg build --buckets) — pack=true also
    # warms the vmapped pack-width programs per rung
    comp = _comp(TENANT_SIZES[0], 0, pack=True)
    comp.global_.run_config["build_buckets"] = True
    t0 = time.time()
    tid = engine.queue_build(comp, manifest, sources_dir=sources)
    engine.start_workers()
    (build,) = _wait(engine, [tid])
    if build.outcome() != Outcome.SUCCESS:
        fail(f"bucket warmup build failed: {build.error}")
    print(
        f"pack-smoke: bucket ladder {LADDER} warmed in "
        f"{time.time() - t0:.1f}s"
    )

    # ---- 2. isolated baseline (bucketed, unpacked, alone)
    iso_n = TENANT_SIZES[0]
    t0 = time.time()
    tid = engine.queue_run(
        _comp(iso_n, 0, pack=False), manifest, sources_dir=sources
    )
    (iso,) = _wait(engine, [tid])
    iso_wall = time.time() - t0
    if iso.outcome() != Outcome.SUCCESS:
        fail(f"isolated baseline failed: {iso.error}")
    iso_sim = (iso.result.get("journal") or {}).get("sim") or {}
    iso_ticks = iso_sim.get("ticks") or 0
    iso_rate = iso_n * iso_ticks / max(iso_wall, 1e-9)
    print(
        f"pack-smoke: isolated run — {iso_ticks} ticks at n={iso_n} in "
        f"{iso_wall:.2f}s ({iso_rate:.0f} peer·ticks/s)"
    )

    # ---- 3. the soak: 8 concurrent tenants, one device. A fresh
    # single-worker engine, with every tenant queued BEFORE the worker
    # starts — the claim is then deterministic (one worker pops the
    # first tenant and claims the other seven in priority order).
    engine.stop()
    engine = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    engine.env.daemon.scheduler.workers = 1
    t0 = time.time()
    tids = [
        engine.queue_run(
            _comp(n, i, pack=True), manifest, sources_dir=sources
        )
        for i, n in enumerate(TENANT_SIZES)
    ]
    engine.start_workers()
    tasks = _wait(engine, tids)
    batch_wall = time.time() - t0

    agg_peer_ticks = 0
    packed_members = 0
    journal_rows = []
    for tsk, n in zip(tasks, TENANT_SIZES):
        if tsk.outcome() != Outcome.SUCCESS:
            fail(f"tenant run {tsk.id} (n={n}) failed: {tsk.error}")
        j = (tsk.result.get("journal") or {})
        sim = j.get("sim") or {}
        bucket = sim.get("bucket") or {}
        pack = sim.get("pack") or {}
        events = (j.get("events") or {}).get("all") or {}
        if bucket.get("compile_cache") != "hit":
            fail(
                f"tenant {tsk.id} (n={n}) paid a COLD compile after the "
                f"bucket warmup: sim.bucket={bucket!r}"
            )
        if bucket.get("instances") != n:
            fail(
                f"tenant {tsk.id}: bucket block reports "
                f"{bucket.get('instances')} live instances, expected {n}"
            )
        if events.get("success") != n:
            fail(
                f"tenant {tsk.id} (n={n}): {events!r} — results are not "
                "exact-N all-success"
            )
        packed_members = max(packed_members, int(pack.get("members") or 1))
        agg_peer_ticks += n * (sim.get("ticks") or 0)
        journal_rows.append(
            {
                "task": tsk.id,
                "n": n,
                "ticks": sim.get("ticks"),
                "pack": pack,
                "compile_cache": bucket.get("compile_cache"),
            }
        )
    if packed_members != len(TENANT_SIZES):
        fail(
            f"expected all {len(TENANT_SIZES)} runs in one pack, saw "
            f"max members={packed_members} (pack admission regressed?)"
        )
    agg_rate = agg_peer_ticks / max(batch_wall, 1e-9)
    need = (len(TENANT_SIZES) / 2) * iso_rate
    print(
        f"pack-smoke: {len(TENANT_SIZES)} tenants in {batch_wall:.2f}s — "
        f"aggregate {agg_rate:.0f} peer·ticks/s vs isolated "
        f"{iso_rate:.0f} (x{agg_rate / max(iso_rate, 1e-9):.1f}, "
        f"max pack members {packed_members})"
    )
    if agg_rate <= need:
        fail(
            f"aggregate throughput {agg_rate:.0f} ≤ N/2 × isolated "
            f"({need:.0f}) — packing is not amortizing the dispatch"
        )

    import json

    for row in journal_rows:
        print("pack-smoke:", json.dumps(row))
    engine.stop()
    shutil.rmtree(home, ignore_errors=True)
    print("pack-smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
