"""``make slo-smoke``: the run health plane's end-to-end contract
(docs/OBSERVABILITY.md "Run health plane") on the CPU backend, driving
the ``plans/chaos`` smoke composition whose schedule makes the declared
``fleet-mostly-alive`` SLO (crashed_fraction < 0.2, warn) breach
DETERMINISTICALLY — 2/8 instances crash at t=6 and restart at t=20:

- **warn severity**: the run still COMPLETES with outcome SUCCESS; the
  breach is recorded — journal ``slo`` rule verdict with breaches > 0,
  ``sim_slo.jsonl`` records, and the ``tg stats`` table's slo line;
- **conservation of breach counts**: journal breach total ==
  ``sim_slo.jsonl`` line count == the per-rule sums;
- **determinism**: a second identical run produces the identical breach
  record stream;
- **fail severity**: the same rule at ``severity = "fail"`` cancels the
  run at the breaching chunk boundary with a typed ``SloBreachError``
  — task outcome FAILURE, the error names the rule, and the archived
  journal KEEPS the run's telemetry record (the fail-fast soak must not
  lose its evidence);
- **loud refusal**: SLOs without ``telemetry = true`` refuse to run.

Exits non-zero with a readable message on any violation. Self-contained:
temporary $TESTGROUND_HOME, CPU backend — safe in CI (mirrors
``tools/chaos_smoke.py``).
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def fail(msg: str) -> "None":
    print(f"slo-smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def _run_once(engine, comp, manifest, sources):
    import time

    from testground_tpu.engine import State

    tid = engine.queue_run(comp, manifest, sources_dir=sources)
    deadline = time.time() + 300
    while time.time() < deadline:
        t = engine.get_task(tid)
        if t is not None and t.state().state in (
            State.COMPLETE,
            State.CANCELED,
        ):
            return t
        time.sleep(0.05)
    fail(f"task {tid} did not finish within 300s")


def _read_slo_rows(env, task):
    from testground_tpu.sim.slo import SLO_FILE

    path = os.path.join(env.dirs.outputs(), "chaos", task.id, SLO_FILE)
    if not os.path.isfile(path):
        fail(f"{SLO_FILE} was not written ({path})")
    rows = []
    with open(path) as f:
        for i, line in enumerate(f):
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError as e:
                fail(f"{SLO_FILE} line {i + 1} is not JSON: {e}")
    return rows


def main() -> int:
    os.environ["TESTGROUND_HOME"] = tempfile.mkdtemp(prefix="tg-slo-")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from testground_tpu.api import TestPlanManifest, load_composition
    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.config import EnvConfig
    from testground_tpu.engine import Engine, EngineConfig, Outcome
    from testground_tpu.runners.pretty import render_telemetry_summary
    from testground_tpu.sim.runner import SimJaxRunner

    plan_dir = os.path.join(REPO_ROOT, "plans", "chaos")
    comp_path = os.path.join(plan_dir, "_compositions", "smoke.toml")
    manifest = TestPlanManifest.load_file(
        os.path.join(plan_dir, "manifest.toml")
    )

    env = EnvConfig.load()
    engine = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    engine.start_workers()
    try:
        # -------------------------------------------- warn severity ×2
        warn_tasks = [
            _run_once(engine, load_composition(comp_path), manifest, plan_dir)
            for _ in range(2)  # second run pins determinism
        ]
        # ------------------------------------------------ fail severity
        comp_fail = load_composition(comp_path)
        comp_fail.global_.run.slo = [
            {
                "name": "fleet-mostly-alive-fatal",
                "metric": "crashed_fraction",
                "op": "<",
                "threshold": 0.2,
                "severity": "fail",
            }
        ]
        fail_task = _run_once(engine, comp_fail, manifest, plan_dir)
        # ------------------------------------------------ loud refusal
        comp_refuse = load_composition(comp_path)
        comp_refuse.global_.run_config["telemetry"] = False
        refuse_task = _run_once(engine, comp_refuse, manifest, plan_dir)
    finally:
        engine.stop()

    # ---- warn: the run completes, the breach is a record, not a death
    task = warn_tasks[0]
    if task.outcome() != Outcome.SUCCESS:
        fail(
            f"warn-severity run outcome {task.outcome().value}: "
            f"{task.error} — a warn SLO must record, never cancel"
        )
    slo = task.result["journal"].get("slo") or {}
    rules = {r["name"]: r for r in slo.get("rules", [])}
    rule = rules.get("fleet-mostly-alive")
    if rule is None:
        fail(f"journal slo block is missing the declared rule: {slo}")
    if not rule.get("breaches"):
        fail(
            "fleet-mostly-alive recorded 0 breaches — the schedule "
            "crashes 25% of the fleet at t=6, the rule must fire"
        )
    if rule.get("severity") != "warn":
        fail(f"rule severity {rule.get('severity')!r} != 'warn'")
    if slo.get("error"):
        fail(f"warn-severity journal carries an error: {slo['error']}")

    # ---- conservation of breach counts: journal == jsonl == rule sums
    rows = _read_slo_rows(env, task)
    total = slo.get("breaches")
    if len(rows) != total:
        fail(
            f"{len(rows)} sim_slo.jsonl record(s) != journal breach "
            f"total {total}"
        )
    per_rule = sum(r.get("breaches", 0) for r in slo.get("rules", []))
    if per_rule != total:
        fail(f"Σ per-rule breaches {per_rule} != journal total {total}")

    # ---- determinism: identical breach record streams
    rows2 = _read_slo_rows(env, warn_tasks[1])
    strip = lambda rs: [  # noqa: E731
        {k: v for k, v in r.items() if k != "run"} for r in rs
    ]
    if strip(rows) != strip(rows2):
        fail("two runs of the same composition produced different "
             "breach record streams — the SLO plane broke determinism")

    # ---- the stats table renders the verdict
    table = render_telemetry_summary(task.stats_payload())
    if "slo fleet-mostly-alive" not in table:
        fail(f"tg stats table has no slo line:\n{table}")

    # ---- fail: typed cancel, journal preserved
    if fail_task.outcome() != Outcome.FAILURE:
        fail(
            f"fail-severity run outcome {fail_task.outcome().value} — a "
            "fail SLO breach must FAIL the task"
        )
    err = fail_task.result.get("error", "") or fail_task.error
    if "SLO breach" not in err or "fleet-mostly-alive-fatal" not in err:
        fail(f"task error does not name the typed SLO breach: {err!r}")
    fj = fail_task.result.get("journal") or {}
    if not (fj.get("slo") or {}).get("error"):
        fail(f"fail-severity journal slo block has no error: {fj.get('slo')}")
    if not fj.get("telemetry", {}).get("rows"):
        fail(
            "fail-fast run lost its telemetry journal — the typed error "
            "must carry the fully-assembled result"
        )
    warn_ticks = task.result["journal"]["sim"]["ticks"]
    fail_ticks = (fj.get("sim") or {}).get("ticks", 0)
    if not 0 < fail_ticks < warn_ticks:
        fail(
            f"fail-fast run executed {fail_ticks} tick(s) vs the "
            f"completed run's {warn_ticks} — it must cancel at the "
            "breaching chunk boundary, not run to completion"
        )

    # ---- refusal: SLOs without telemetry never run silently unenforced
    if refuse_task.outcome() != Outcome.FAILURE:
        fail(
            "declaring SLOs with telemetry=false must refuse loudly, "
            f"got outcome {refuse_task.outcome().value}"
        )
    if "telemetry" not in (refuse_task.error or ""):
        fail(
            f"refusal error does not name the telemetry plane: "
            f"{refuse_task.error!r}"
        )

    print(
        "slo-smoke: OK — warn rule breached {b} time(s) (recorded, run "
        "SUCCESS), records conserved + deterministic, fail rule canceled "
        "at tick {ft} of {wt} with a typed SloBreachError (journal "
        "preserved), telemetry-off refusal loud".format(
            b=rule["breaches"], ft=fail_ticks, wt=warn_ticks
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
