"""``make telemetry-smoke``: run a tiny composition with the telemetry
plane on and assert the contract end-to-end — ``sim_timeseries.jsonl``
exists, is non-empty, every row is schema-valid, and the per-tick sums
equal the journal's cumulative totals exactly (conservation).

Exits non-zero with a readable message on any violation; prints a
one-line summary on success. Self-contained: runs against a temporary
$TESTGROUND_HOME on the CPU backend, so it is safe in CI.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def fail(msg: str) -> "None":
    print(f"telemetry-smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> int:
    os.environ["TESTGROUND_HOME"] = tempfile.mkdtemp(prefix="tg-smoke-")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from tests.test_sim_runner import run_sim
    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.config import EnvConfig
    from testground_tpu.engine import Engine, EngineConfig, Outcome
    from testground_tpu.sim.runner import SimJaxRunner
    from testground_tpu.sim.telemetry import (
        SIM_SERIES_FILE,
        TELEMETRY_FIXED_COLUMNS,
        telemetry_totals,
    )

    env = EnvConfig.load()
    engine = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    engine.start_workers()
    try:
        task = run_sim(
            engine,
            "network",
            "ping-pong",
            instances=2,
            run_params={"telemetry": True, "chunk": 16},
        )
    finally:
        engine.stop()
    if task.outcome() != Outcome.SUCCESS:
        fail(f"run outcome {task.outcome().value}: {task.error}")

    path = os.path.join(
        env.dirs.outputs(), "network", task.id, SIM_SERIES_FILE
    )
    if not os.path.isfile(path):
        fail(f"{SIM_SERIES_FILE} was not written ({path})")
    rows = []
    with open(path) as f:
        for i, line in enumerate(f):
            try:
                row = json.loads(line)
            except json.JSONDecodeError as e:
                fail(f"line {i + 1} is not JSON: {e}")
            for col in ("run", "plan", "case", *TELEMETRY_FIXED_COLUMNS):
                if col not in row:
                    fail(f"line {i + 1} missing column {col!r}")
            for col in TELEMETRY_FIXED_COLUMNS:
                if not isinstance(row[col], int):
                    fail(f"line {i + 1}: {col} is not an int")
            if not isinstance(row.get("live"), dict):
                fail(f"line {i + 1}: 'live' is not a per-group map")
            rows.append(row)
    if not rows:
        fail(f"{SIM_SERIES_FILE} is empty")

    sim = task.result["journal"]["sim"]
    for col, got in telemetry_totals(rows).items():
        want = sim[f"msgs_{col}"]
        if got != want:
            fail(f"Σ {col} = {got} != journal msgs_{col} = {want}")

    print(
        f"telemetry-smoke: OK — {len(rows)} rows, "
        f"delivered={sim['msgs_delivered']} dropped={sim['msgs_dropped']} "
        f"rejected={sim['msgs_rejected']} carry={sim['carry_bytes']}B"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
