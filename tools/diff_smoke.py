"""``make diff-smoke``: drive differential run analysis end to end
through a real daemon — the CI teeth for ``tg diff`` and the bench
sentinel (docs/OBSERVABILITY.md "Run diff / bench sentinel"):

1. two identically-seeded ping-pong runs diff CLEAN through the real
   CLI (``tg diff --endpoint``): every deterministic plane reports
   exact equality, zero correctness findings, and the noise-aware
   throughput judgment reports zero significant deltas;
2. a third run deliberately slowed with ``debug_chunk_sleep_ms`` (the
   synthetic-slowdown debug knob — inflates chunk walls without
   touching program semantics) is flagged ``regressed`` with an
   auditable Mann–Whitney p-value;
3. the bench sentinel round-trips: a tiny ``bench.py --bank`` run
   banks against a copy of the committed BENCH_HISTORY.jsonl and
   ``tools/bench_regression.py`` passes (inconclusive rows pass but
   are journaled), then a fabricated 3x-slower row flips it to a
   non-zero exit.

Exits non-zero with a readable message on any violation; prints a
one-line summary on success. Self-contained: runs against a temporary
$TESTGROUND_HOME on the CPU backend, so it is safe in CI. A warmup run
precedes the A/B pair so cold-compile asymmetry cannot masquerade as a
throughput shift.
"""

import io
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from contextlib import redirect_stdout

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

RUN_CONFIG = {"telemetry": True, "chunk": 16, "max_ticks": 512}


def fail(msg: str) -> "None":
    print(f"diff-smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def tg(args) -> tuple[int, str]:
    """Invoke the real CLI entry point, capturing stdout."""
    from testground_tpu.cli.main import main

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = main(args)
    return rc, buf.getvalue()


def main() -> int:
    os.environ["TESTGROUND_HOME"] = tempfile.mkdtemp(prefix="tg-smoke-")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from testground_tpu.client import Client
    from testground_tpu.config import EnvConfig
    from testground_tpu.daemon import Daemon

    daemon = Daemon(env=EnvConfig.load(), listen="localhost:0")
    daemon.start()
    try:
        client = Client(daemon.address)
        client.import_plan(os.path.join(REPO_ROOT, "plans", "network"))

        def run(name, extra=None):
            cfg = dict(RUN_CONFIG, **(extra or {}))
            tid = client.run(
                {
                    "metadata": {"name": name},
                    "global": {
                        "plan": "network",
                        "case": "ping-pong",
                        "builder": "sim:plan",
                        "runner": "sim:jax",
                        "run_config": cfg,
                    },
                    "groups": [
                        {"id": "ping", "instances": {"count": 1}},
                        {"id": "pong", "instances": {"count": 1}},
                    ],
                }
            )
            deadline = time.time() + 180
            while time.time() < deadline:
                t = client.status(tid)
                if t["states"][-1]["state"] in ("complete", "canceled"):
                    break
                time.sleep(0.2)
            else:
                fail(f"task {tid} ({name}) did not finish")
            if t.get("error"):
                fail(f"run {name} errored: {t['error']}")
            return tid

        # warmup: the first in-process run pays cold-compile and
        # first-touch costs that would otherwise shift the A/B medians
        run("diff-smoke-warmup")
        a = run("diff-smoke-a")
        b = run("diff-smoke-b")

        # --- 1. identically-seeded pair diffs clean through the CLI
        rc, screen = tg(["--endpoint", daemon.address, "diff", a, b])
        if rc != 0:
            fail(f"tg diff on identical runs exited {rc}:\n{screen}")
        if "exact equality" not in screen:
            fail(f"screen is missing the exact-equality verdict:\n{screen}")
        if "MISMATCH" in screen:
            fail(f"identical runs report a counter mismatch:\n{screen}")
        if "regressed" in screen or "improved" in screen:
            fail(f"identical runs report a throughput shift:\n{screen}")
        rc, out = tg(["--endpoint", daemon.address, "diff", a, b, "--json"])
        if rc != 0:
            fail(f"tg diff --json exited {rc}")
        doc = json.loads(out)
        if doc["findings"]:
            fail(f"identical runs yield findings: {doc['findings']}")
        if not doc["setup"]["identical"]:
            fail("identical compositions not recognised as identical")
        ctr = doc["counters"]
        if ctr.get("mismatched") != 0 or not ctr.get("compared"):
            fail(f"counters plane not exactly equal: {ctr}")
        shifted = [
            r
            for r in doc["perf"].get("metrics", [])
            if r["verdict"] in ("regressed", "improved")
        ]
        if shifted:
            fail(f"identical runs judged shifted: {shifted}")

        # --- 2. the deliberately-slowed run is flagged regressed
        slow = run("diff-smoke-slow", {"debug_chunk_sleep_ms": 25})
        rc, out = tg(
            ["--endpoint", daemon.address, "diff", a, slow, "--json"]
        )
        if rc != 0:
            # the slowed run differs only in a debug knob: no
            # correctness findings, so the exit code stays 0
            fail(f"tg diff vs slowed run exited {rc}")
        sdoc = json.loads(out)
        regressed = {
            r["metric"]: r
            for r in sdoc["perf"].get("metrics", [])
            if r["verdict"] == "regressed"
        }
        if "chunk_ticks_per_sec" not in regressed:
            fail(
                "slowed run not flagged regressed on chunk_ticks_per_sec: "
                f"{sdoc['perf'].get('metrics')}"
            )
        pval = regressed["chunk_ticks_per_sec"]["p_value"]
        if not (isinstance(pval, float) and pval < 0.01):
            fail(f"regression p-value not significant: {pval}")
        if sdoc["verdict"] != "regressed":
            fail(f"rollup verdict {sdoc['verdict']!r} != 'regressed'")

        # --- 3. bench sentinel round-trip against the committed bank
        tmp = os.path.join(os.environ["TESTGROUND_HOME"], "history.jsonl")
        shutil.copy(os.path.join(REPO_ROOT, "BENCH_HISTORY.jsonl"), tmp)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        bench = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO_ROOT, "bench.py"),
                "--instances", "512",
                "--ticks", "512",
                "--skip-secondary",
                "--bank",
                "--history", tmp,
            ],
            capture_output=True,
            text=True,
            env=env,
            cwd=REPO_ROOT,
            timeout=600,
        )
        if bench.returncode != 0:
            fail(f"tiny bench --bank exited {bench.returncode}:\n{bench.stderr}")
        if "# banked" not in bench.stderr:
            fail("bench.py --bank did not report banking")
        sentinel = [
            sys.executable,
            os.path.join(REPO_ROOT, "tools", "bench_regression.py"),
            "--history", tmp,
        ]
        ok = subprocess.run(
            sentinel, capture_output=True, text=True, env=env, timeout=120
        )
        if ok.returncode != 0:
            fail(
                f"sentinel failed against committed baseline "
                f"(rc {ok.returncode}):\n{ok.stdout}\n{ok.stderr}"
            )
        # fabricate a confident regression: clone the freshly-banked
        # row (guaranteed key match) at a third of its value
        with open(tmp) as f:
            last = json.loads(f.readlines()[-1])
        last["value"] = last["value"] / 3.0
        last["ts"] = "9999-01-01T00:00:00+00:00"
        with open(tmp, "a") as f:
            f.write(json.dumps(last, sort_keys=True) + "\n")
        bad = subprocess.run(
            sentinel, capture_output=True, text=True, env=env, timeout=120
        )
        if bad.returncode != 1:
            fail(
                f"sentinel did not flag the 3x-slower row "
                f"(rc {bad.returncode}):\n{bad.stdout}\n{bad.stderr}"
            )
        if "regressed" not in bad.stdout:
            fail(f"sentinel output lacks a regressed verdict:\n{bad.stdout}")
    finally:
        daemon.stop()

    n_judged = len(sdoc["perf"].get("metrics", []))
    print(
        f"diff-smoke: OK — counters {ctr['compared']} exact, "
        f"{n_judged} judged metrics, slowdown p={pval:.2e} "
        f"x{regressed['chunk_ticks_per_sec']['ratio']:.3f}, "
        f"sentinel ok→regressed round-trip"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
