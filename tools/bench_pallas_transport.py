"""A/B harness for the Pallas transport kernels (PERF.md "Pallas
transport kernels"; ISSUE 5, segmented + cost-model rungs in ISSUE 14).

Runs the SAME workload per transport backend — ``xla`` (the scatter
path PERF.md profiles at 84% of the sustained tick) and ``pallas``
(``sim/pallas_transport.py``, the segmented VMEM-streaming kernels) —
on a single device, and reports steady-state per-tick wall,
peer·ticks/s, and the ratio, as one JSON line. Compile time is excluded
from the per-tick number and reported alongside (both backends pay
their own trace + compile/cache-read).

Every rung also records the ``transport=auto`` cost model's verdict for
that shape (``transport_choice`` in the JSON — requested/resolved/
reason/scores), so a bench round doubles as a model-vs-measurement
audit. ``--transport auto`` measures ONLY the backend the model picks;
``--rungs`` sweeps instance counts in one invocation — the segmented
kernel admits the >500k and storm-shaped rungs the ISSUE-5 kernel's
whole-stream VMEM envelope excluded:

    python tools/bench_pallas_transport.py --instances 100000 --ticks 2048
    python tools/bench_pallas_transport.py --workload storm \\
        --rungs 100000,250000 --ticks 512
    python tools/bench_pallas_transport.py --rungs 262144,524288,786432 \\
        --ticks 256 --transport auto

On CPU the kernels run under the Pallas interpreter, so the numbers are
FUNCTIONAL only (the interpreter emulates the kernel op by op and is
orders of magnitude off real kernel cost) — the tool still verifies the
two backends agree on the workload's flow totals before timing, so a
CPU run is a correctness gate, not a perf claim. The default sizes are
CPU-safe; pass the 100k/2048 shape above on hardware. A real-chip JSON
saved as ``BENCH_PALLAS*.json`` beside the repo becomes a BANKED
verdict the ``transport=auto`` model reads (sim/transport_model.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

WORKLOADS = {
    # the primary PERF.md target: general sorted transport, provenance
    # plane on, cross-tick stacking, 7/8 shaping features — the three
    # hot ops the kernels replace all live here
    "sustained": (
        "network",
        "pingpong-sustained",
        lambda ticks: {
            "duration_ticks": str(10 * ticks),
            "latency_ms": "4",
            "latency2_ms": "2",
            "reshape_every": "1000",
        },
    ),
    # direct slot mode: only the delivery kernel applies (the commit
    # kernel needs the sort's bucket ordering) — isolates the pop fusion
    "flood": (
        "benchmarks",
        "pingpong-flood",
        lambda ticks: {"duration_ticks": str(10 * ticks), "latency_ms": "4"},
    ),
    # storm-shaped fan-out (OUT_MSGS·IN_MSGS large, Poisson fan-in over
    # a random graph): the shape whose sorted-stream footprint blew the
    # ISSUE-5 whole-stream VMEM envelope well below 100k — admissible
    # since the segmented kernel, and the adversarial rung for the
    # tile-boundary rank carry (multi-message runs everywhere)
    "storm": (
        "benchmarks",
        "storm",
        # one 4 KiB chunk per connection per tick — size the payload so
        # the flood phase outlasts the measurement window
        lambda ticks: {
            "conn_outgoing": "3",
            "conn_delay_ticks": "8",
            "data_size_kb": str(4 * (10 * ticks)),
        },
    ),
}


def _build(plan, case, n, params, chunk, transport):
    from testground_tpu.api import RunGroup
    from testground_tpu.sim.engine import SimProgram, build_groups
    from testground_tpu.sim.executor import (
        instantiate_testcase,
        load_sim_testcases,
    )

    factory = load_sim_testcases(os.path.join(REPO_ROOT, "plans", plan))[case]
    groups = build_groups([RunGroup(id="all", instances=n, parameters=params)])
    tc = instantiate_testcase(factory, groups, tick_ms=1.0)
    return SimProgram(
        tc,
        groups,
        test_plan=plan,
        test_case=case,
        tick_ms=1.0,
        mesh=None,  # single-device A/B: identical topology both arms
        chunk=chunk,
        transport=transport,
    )


def _decide(prog, plan, case, chunk):
    """The transport=auto verdict for this rung's shape — the same
    decision path every runtime gate takes (sim/transport_model.py)."""
    import types

    from testground_tpu.sim.transport_model import (
        TransportContext,
        decide_transport,
    )

    return decide_transport(
        types.SimpleNamespace(transport="auto"),
        None,
        context=TransportContext(
            testcase=prog.tc,
            groups=tuple(prog.groups),
            test_plan=plan,
            test_case=case,
            chunk=chunk,
        ),
    )


def _measure(prog, ticks: int) -> dict:
    # bench.py's warm-then-time loop IS the measurement (one code path
    # for the D2H-sync and done-break details); only the flow extraction
    # and the per-tick normalization live here
    from bench import _timed_ticks

    carry, run_ticks, wall, compile_secs = _timed_ticks(prog, ticks)
    run_ticks = max(run_ticks, 1)
    return {
        "compile_secs": round(compile_secs, 3),
        "ticks": run_ticks,
        "wall_secs": round(wall, 4),
        "ms_per_tick": round(1e3 * wall / run_ticks, 4),
        "peer_ticks_per_sec": round(prog.n * run_ticks / wall, 1),
        "flow": {
            "delivered": _acc(carry.msgs_delivered),
            "sent": _acc(carry.msgs_sent),
            "enqueued": _acc(carry.msgs_enqueued),
            "dropped": _acc(carry.msgs_dropped),
        },
    }


def _acc(limb) -> int:
    from testground_tpu.sim.engine import _acc_total

    import numpy as np

    return _acc_total(np.asarray(limb))


def _print_phase_ab(out: dict) -> None:
    """Per-phase A/B split to stderr: measured ms/tick when the
    calibration ran (the chip evidence), XLA bytes-accessed otherwise.
    The transport phases (deliver + net_commit — the ops the kernels
    replace) are where the verdict lives; the rest should be ~equal and
    any drift there flags a mis-attributed win."""
    from testground_tpu.sim.phases import TICK_PHASES

    px = {
        r["phase"]: r
        for r in (out["xla"].get("phases") or {}).get("phases", [])
    }
    pp = {
        r["phase"]: r
        for r in (out["pallas"].get("phases") or {}).get("phases", [])
    }
    for name in TICK_PHASES:
        a, b = px.get(name), pp.get(name)
        if a is None and b is None:
            continue
        key, unit = (
            ("measured_ms", "ms")
            if (a or {}).get("measured_ms") is not None
            and (b or {}).get("measured_ms") is not None
            else ("bytes_accessed", "B")
        )
        va = (a or {}).get(key)
        vb = (b or {}).get(key)
        ratio = (
            f" (pallas_vs_xla x{va / vb:.3f})" if va and vb else ""
        )
        print(
            f"# phase {name}: xla "
            f"{va if va is not None else '?'}{unit} vs pallas "
            f"{vb if vb is not None else '?'}{unit}{ratio}",
            file=sys.stderr,
        )


def _run_rung(args, plan, case, params_of, n: int) -> int | dict:
    """One instance-count rung: build, record the cost-model choice,
    measure the requested arm(s), cross-check flow when both ran.
    Returns the rung dict, or a nonzero exit code on divergence."""
    rung: dict = {"instances": n}
    params = params_of(args.ticks)
    base = _build(plan, case, n, params, args.chunk, "xla")
    decision = _decide(base, plan, case, args.chunk)
    rung["transport_choice"] = decision.block()
    print(
        f"# rung {n}: auto -> {decision.resolved} ({decision.reason})",
        file=sys.stderr,
    )
    arms = {
        "both": ("xla", "pallas"),
        "xla": ("xla",),
        "pallas": ("pallas",),
        "auto": (decision.resolved,),
    }[args.transport]
    for transport in arms:
        prog = (
            base
            if transport == "xla"
            else _build(plan, case, n, params, args.chunk, transport)
        )
        rung[transport] = _measure(prog, args.ticks)
        if args.phases:
            from testground_tpu.sim.phases import build_phase_ledger

            rung[transport]["phases"] = build_phase_ledger(
                prog, measure=max(0, args.phase_reps)
            )
        print(
            f"# {transport}@{n}: {rung[transport]['ms_per_tick']} ms/tick "
            f"(+{rung[transport]['compile_secs']}s compile)",
            file=sys.stderr,
        )
    if "xla" in rung and "pallas" in rung:
        if args.phases:
            _print_phase_ab(rung)
        if rung["xla"]["flow"] != rung["pallas"]["flow"]:
            print(
                "bench_pallas_transport: FAIL — flow totals diverge "
                f"between backends at {n} instances: "
                f"xla={rung['xla']['flow']} pallas={rung['pallas']['flow']}",
                file=sys.stderr,
            )
            return 1
        rung["pallas_vs_xla"] = round(
            rung["xla"]["ms_per_tick"] / rung["pallas"]["ms_per_tick"], 3
        )
    return rung


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--instances", type=int, default=2048)
    p.add_argument(
        "--rungs",
        default="",
        help="comma-separated instance counts — sweep several rungs in "
        "one invocation (overrides --instances); the JSON line then "
        "carries a per-rung `rungs` list",
    )
    p.add_argument("--ticks", type=int, default=256)
    p.add_argument("--chunk", type=int, default=64)
    p.add_argument(
        "--workload", choices=sorted(WORKLOADS), default="sustained"
    )
    # which arm(s) to measure: "both" is the classic A/B; "auto" runs
    # ONLY the backend the cost model picks for each rung (the
    # production posture) — the choice itself is recorded either way
    p.add_argument(
        "--transport",
        choices=("both", "auto", "xla", "pallas"),
        default="both",
    )
    # per-backend phase attribution (sim/phases.py): bank the chip
    # verdict WITH the per-phase split in one command (ROADMAP item 1) —
    # each backend's ledger lands in the JSON line and the per-phase A/B
    # ratio prints alongside the headline ms/tick. --phase-reps times
    # each phase jitted in isolation (measured ms/tick — the per-op
    # evidence); 0 keeps the static XLA cost rows only.
    p.add_argument("--phases", action="store_true")
    p.add_argument("--phase-reps", type=int, default=30)
    args = p.parse_args()

    from testground_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    import jax

    plan, case, params_of = WORKLOADS[args.workload]
    backend = jax.default_backend()
    interpreted = backend != "tpu"
    rungs = (
        [int(r) for r in args.rungs.split(",") if r.strip()]
        if args.rungs
        else [args.instances]
    )
    print(
        f"# pallas-transport A/B: {args.workload} @ "
        f"{','.join(str(r) for r in rungs)} instances × {args.ticks} "
        f"ticks on {backend} (arm: {args.transport})"
        + (" (pallas INTERPRETED — functional gate, not a perf claim)"
           if interpreted else ""),
        file=sys.stderr,
    )
    out = {
        "workload": args.workload,
        "ticks": args.ticks,
        "backend": backend,
        "pallas_interpreted": interpreted,
        "transport_arm": args.transport,
    }
    results = []
    for n in rungs:
        rung = _run_rung(args, plan, case, params_of, n)
        if isinstance(rung, int):
            return rung
        results.append(rung)
    if len(results) == 1 and not args.rungs:
        # classic single-rung schema, unchanged for existing consumers
        # (+ the transport_choice block every rung now carries)
        out.update(results[0])
    else:
        out["rungs"] = results
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
