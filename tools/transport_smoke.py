"""``make transport-smoke``: the ``transport=auto`` cost model and the
segmented pallas commit kernel, end-to-end on CPU (ISSUE 14) —

- **contrasting shapes pick both backends**: interpret-mode static
  scoring resolves the sorted flagship shape to ``pallas`` (commit
  bytes clear the margin) and the direct-slot flood shape to ``xla``
  (hard gate) — both backends chosen at least once, deterministically;
- **the journal carries the decision**: a tiny composition run with
  ``transport=auto`` journals ``sim.transport {requested, resolved,
  reason, scores}``, the ``tg stats`` line renders it, and the
  Prometheus exposition carries the ``tg_transport_resolved`` info
  gauge;
- **bit-equality spot check**: the same sorted workload through
  ``transport=xla`` and ``transport=pallas`` (segmented kernel,
  interpreted) agrees on status and every flow total, with a tile
  small enough that the stream actually spans tile boundaries.

Exits non-zero with a readable message on any violation; prints a
one-line summary on success. Self-contained: runs against a temporary
$TESTGROUND_HOME on the CPU backend, so it is safe in CI.
"""

import dataclasses
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# force multi-tile streams even at smoke scale: the equality check must
# cross tile boundaries, not fit one tile (must be set before jax/pallas
# trace anything)
os.environ["TG_TRANSPORT_TILE"] = "128"

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def fail(msg: str) -> "None":
    print(f"transport-smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> int:
    os.environ["TESTGROUND_HOME"] = tempfile.mkdtemp(prefix="tg-smoke-")
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import __graft_entry__ as ge
    from testground_tpu.sim.transport_model import (
        TransportContext,
        decide_transport,
    )

    cfg_cls = dataclasses.make_dataclass("Cfg", [("transport", str)])

    # ---------------------------------------- 1. contrasting decisions
    def decide(prog, plan, case):
        return decide_transport(
            cfg_cls("auto"),
            None,
            context=TransportContext(
                testcase=prog.tc,
                groups=tuple(prog.groups),
                test_plan=plan,
                test_case=case,
                chunk=prog.chunk,
            ),
        )

    sorted_prog = ge._plan_program(
        "network",
        "pingpong-sustained",
        512,
        {
            "duration_ticks": "640",
            "latency_ms": "4",
            "latency2_ms": "2",
            "reshape_every": "1000",
        },
    )
    d_sorted = decide(sorted_prog, "network", "pingpong-sustained")
    if d_sorted.resolved != "pallas":
        fail(
            "sorted flagship shape resolved to "
            f"{d_sorted.resolved!r}, expected pallas ({d_sorted.reason})"
        )
    if not (d_sorted.scores or {}).get("ratio"):
        fail(f"sorted decision carries no scores: {d_sorted.block()}")
    flood_prog = ge._plan_program(
        "benchmarks",
        "pingpong-flood",
        512,
        {"duration_ticks": "640", "latency_ms": "4"},
    )
    d_flood = decide(flood_prog, "benchmarks", "pingpong-flood")
    if d_flood.resolved != "xla":
        fail(
            f"direct-slot flood shape resolved to {d_flood.resolved!r}, "
            "expected xla"
        )
    # determinism: the same context must yield the identical decision
    if decide(sorted_prog, "network", "pingpong-sustained") is not d_sorted:
        fail("decision cache missed on an identical context")

    # ------------------------------------- 2. journal + surfaces
    from tests.test_sim_runner import run_sim
    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.config import EnvConfig
    from testground_tpu.engine import Engine, EngineConfig, Outcome
    from testground_tpu.metrics.prometheus import render_prometheus
    from testground_tpu.runners.pretty import render_telemetry_summary
    from testground_tpu.sim.runner import SimJaxRunner

    env = EnvConfig.load()
    engine = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    engine.start_workers()
    try:
        task = run_sim(
            engine,
            "network",
            "ping-pong",
            instances=4,
            run_params={"chunk": 16, "transport": "auto"},
        )
    finally:
        engine.stop()
    if task.outcome() != Outcome.SUCCESS:
        fail(f"auto run outcome {task.outcome().value}: {task.error}")
    block = task.result["journal"]["sim"].get("transport") or {}
    if block.get("requested") != "auto":
        fail(f"journal sim.transport requested != auto: {block}")
    if block.get("resolved") not in ("xla", "pallas"):
        fail(f"journal sim.transport resolved is bogus: {block}")
    if not block.get("reason"):
        fail(f"journal sim.transport has no reason: {block}")
    stats = render_telemetry_summary(
        {"plan": "network", "case": "ping-pong", **task.result["journal"]}
    )
    if "transport" not in stats or "auto" not in stats:
        fail(f"tg stats render lacks the transport line:\n{stats}")
    text = render_prometheus([task])
    if "\ntg_transport_resolved{" not in text:
        fail("tg_transport_resolved absent from the Prometheus exposition")
    if 'requested="auto"' not in text:
        fail("tg_transport_resolved lacks the requested=auto label")

    # ------------------------------------- 3. bit-equality spot check
    res_x = ge._pingpong_program(8, transport="xla").run(max_ticks=256)
    res_p = ge._pingpong_program(8, transport="pallas").run(max_ticks=256)
    for key in (
        "status",
        "msgs_delivered",
        "msgs_sent",
        "msgs_enqueued",
        "msgs_dropped",
        "msgs_rejected",
        "cal_depth",
    ):
        a, b = np.asarray(res_x[key]), np.asarray(res_p[key])
        if not np.array_equal(a, b):
            fail(f"xla vs pallas {key} mismatch: {a} vs {b}")
    if not res_x["msgs_delivered"] > 0:
        fail("equality spot check moved no traffic")

    print(
        "transport-smoke: OK — sorted→pallas "
        f"(ratio x{(d_sorted.scores or {}).get('ratio')}), "
        "flood→xla (direct gate), journal "
        f"auto→{block.get('resolved')}, equality over "
        f"{res_x['msgs_delivered']} delivered msgs"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
