"""``make perf-smoke``: run a tiny composition and assert the
performance-ledger contract end-to-end (docs/OBSERVABILITY.md
"Performance ledger") —

- the journal carries a ``sim.perf`` block (compile split, execute
  gauges, per-chunk series reference);
- ``sim_perf.jsonl`` exists, every row is schema-valid, and the rows'
  per-chunk walls sum exactly to the ledger's ``execute.wall_secs``
  (which in turn must fit inside the run's wall);
- chunk accounting conserves: row count == ``execute.chunks`` and the
  last row's tick == ``execute.ticks`` == the dispatched tick count;
- on CPU the AOT pass harvests XLA cost analysis, so the estimated
  FLOPs / bytes-accessed fields are present and non-zero (tolerated
  absent on backends that expose no estimate — reported, not failed).

Exits non-zero with a readable message on any violation; prints a
one-line summary on success. Self-contained: runs against a temporary
$TESTGROUND_HOME on the CPU backend, so it is safe in CI.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def fail(msg: str) -> "None":
    print(f"perf-smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> int:
    os.environ["TESTGROUND_HOME"] = tempfile.mkdtemp(prefix="tg-smoke-")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from tests.test_sim_runner import run_sim
    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.config import EnvConfig
    from testground_tpu.engine import Engine, EngineConfig, Outcome
    from testground_tpu.sim.runner import SimJaxRunner
    from testground_tpu.sim.telemetry import PERF_FILE

    env = EnvConfig.load()
    engine = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    engine.start_workers()
    try:
        task = run_sim(
            engine,
            "network",
            "ping-pong",
            instances=2,
            run_params={"chunk": 16},
        )
    finally:
        engine.stop()
    if task.outcome() != Outcome.SUCCESS:
        fail(f"run outcome {task.outcome().value}: {task.error}")

    sim = task.result["journal"]["sim"]
    perf = sim.get("perf")
    if not perf:
        fail("journal sim.perf block is absent")
    ex = perf.get("execute") or {}
    for key in ("chunks", "ticks", "wall_secs", "peer_ticks_per_sec"):
        if not ex.get(key):
            fail(f"sim.perf.execute.{key} missing or zero")
    if ex["ticks"] != sim["ticks"]:
        fail(f"execute.ticks {ex['ticks']} != journal ticks {sim['ticks']}")
    co = perf.get("compile") or {}
    if not co:
        fail("sim.perf.compile block absent (AOT accounting did not run)")
    for key in ("lower_secs", "compile_secs"):
        if key not in co:
            fail(f"sim.perf.compile.{key} missing")
    cost_note = ""
    if "flops" in co or "bytes_accessed" in co:
        # where the backend estimates at all, the fields must be real
        for key in ("flops", "bytes_accessed"):
            if key in co and not co[key] > 0:
                fail(f"sim.perf.compile.{key} present but not > 0")
    else:
        cost_note = " (no cost analysis on this backend)"

    path = os.path.join(env.dirs.outputs(), "network", task.id, PERF_FILE)
    if not os.path.isfile(path):
        fail(f"{PERF_FILE} was not written ({path})")
    rows = []
    with open(path) as f:
        for i, line in enumerate(f):
            try:
                row = json.loads(line)
            except json.JSONDecodeError as e:
                fail(f"line {i + 1} is not JSON: {e}")
            for col in (
                "run",
                "plan",
                "case",
                "tick",
                "chunk",
                "wall_secs",
                "ticks_per_sec",
                "peer_ticks_per_sec",
            ):
                if col not in row:
                    fail(f"line {i + 1} missing column {col!r}")
            rows.append(row)
    if not rows:
        fail(f"{PERF_FILE} is empty")
    if len(rows) != ex["chunks"]:
        fail(f"{len(rows)} rows != execute.chunks {ex['chunks']}")
    if rows[-1]["tick"] != ex["ticks"]:
        fail(f"last row tick {rows[-1]['tick']} != execute.ticks")
    wall_sum = sum(r["wall_secs"] for r in rows)
    if abs(wall_sum - ex["wall_secs"]) > 1e-3 + 0.01 * ex["wall_secs"]:
        fail(
            f"Σ per-chunk wall {wall_sum:.6f}s !≈ execute.wall_secs "
            f"{ex['wall_secs']:.6f}s"
        )
    if wall_sum > sim["wall_secs"]:
        fail(
            f"Σ per-chunk wall {wall_sum:.3f}s exceeds the run wall "
            f"{sim['wall_secs']:.3f}s"
        )

    print(
        f"perf-smoke: OK — {len(rows)} chunk rows, "
        f"{ex['peer_ticks_per_sec']:.0f} peer·ticks/s, lower "
        f"{co['lower_secs']:.2f}s + xla {co['compile_secs']:.2f}s"
        f"{cost_note}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
