"""``make phases-smoke``: run a tiny composition with the phase
attribution plane armed and assert its contract end-to-end
(docs/OBSERVABILITY.md "Phase attribution") —

- the journal carries a ``sim.phases`` block: one row per compiled-in
  tick phase (telemetry on → deliver / lat_hist / step / sync /
  net_commit / telemetry; no faults declared → no faults row), plus the
  whole-program and residual rows;
- conservation BY CONSTRUCTION: for every cost field present,
  Σ phases + residual == whole_per_tick (to the block's rounding);
- the measured calibration (``phases_measure``) stamped every phase
  with a positive ms/tick;
- ``sim_phases.jsonl`` exists and mirrors the journal block row for
  row (phases + residual + total, each tagged with the run identity
  and transport);
- the console table renders and the Prometheus exposition carries
  ``tg_phase_*`` gauges for the task.

Exits non-zero with a readable message on any violation; prints a
one-line summary on success. Self-contained: runs against a temporary
$TESTGROUND_HOME on the CPU backend, so it is safe in CI.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def fail(msg: str) -> "None":
    print(f"phases-smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> int:
    os.environ["TESTGROUND_HOME"] = tempfile.mkdtemp(prefix="tg-smoke-")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from tests.test_sim_runner import run_sim
    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.config import EnvConfig
    from testground_tpu.engine import Engine, EngineConfig, Outcome
    from testground_tpu.metrics.prometheus import render_prometheus
    from testground_tpu.runners.pretty import render_phase_table
    from testground_tpu.sim.phases import PHASES_FILE
    from testground_tpu.sim.runner import SimJaxRunner

    env = EnvConfig.load()
    engine = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    engine.start_workers()
    try:
        task = run_sim(
            engine,
            "network",
            "ping-pong",
            instances=2,
            run_params={
                "chunk": 16,
                "telemetry": True,
                "phases": True,
                "phases_measure": 2,
            },
        )
    finally:
        engine.stop()
    if task.outcome() != Outcome.SUCCESS:
        fail(f"run outcome {task.outcome().value}: {task.error}")

    sim = task.result["journal"]["sim"]
    block = sim.get("phases")
    if not block:
        fail("journal sim.phases block is absent")
    rows = block.get("phases") or []
    names = [r.get("phase") for r in rows]
    expected = ["deliver", "lat_hist", "step", "sync", "net_commit", "telemetry"]
    if names != expected:
        fail(f"phase rows {names} != expected {expected}")
    whole = block.get("whole_per_tick") or {}
    residual = block.get("residual") or {}
    if not whole:
        fail("whole_per_tick is empty (no cost analysis on CPU?)")
    for key, total in whole.items():
        s = sum(float(r.get(key, 0.0) or 0.0) for r in rows)
        if abs(s + residual.get(key, 0.0) - total) > 0.02 + 1e-6 * abs(total):
            fail(
                f"Σ phases[{key}] {s} + residual {residual.get(key)} != "
                f"whole {total}"
            )
    for r in rows:
        if not (r.get("measured_ms") or 0) > 0:
            fail(f"phase {r.get('phase')}: measured_ms missing or <= 0")
    if block.get("transport") != "xla":
        fail(f"transport tag {block.get('transport')!r} != 'xla'")

    path = os.path.join(env.dirs.outputs(), "network", task.id, PHASES_FILE)
    if not os.path.isfile(path):
        fail(f"{PHASES_FILE} was not written ({path})")
    jrows = []
    with open(path) as f:
        for i, line in enumerate(f):
            try:
                jrows.append(json.loads(line))
            except json.JSONDecodeError as e:
                fail(f"line {i + 1} is not JSON: {e}")
    jnames = [r.get("phase") for r in jrows]
    if jnames != expected + ["residual", "total"]:
        fail(f"jsonl rows {jnames} != journal phases + residual + total")
    for r in jrows:
        for col in ("run", "plan", "case", "transport", "phase"):
            if col not in r:
                fail(f"jsonl row {r.get('phase')} missing column {col!r}")
    series = block.get("series") or {}
    if series.get("rows") != len(jrows):
        fail(f"series.rows {series.get('rows')} != {len(jrows)} jsonl rows")

    table = render_phase_table({"phases": block})
    if "residual" not in table or "net_commit" not in table:
        fail(f"rendered table lacks expected rows:\n{table}")
    text = render_prometheus([task])
    for metric in (
        "tg_phase_flops",
        "tg_phase_bytes_accessed",
        "tg_phase_measured_ms",
    ):
        if f"\n{metric}{{" not in text:
            fail(f"{metric} absent from the Prometheus exposition")
    if 'phase="residual"' not in text or 'phase="total"' not in text:
        fail("residual/total phase rows absent from the exposition")

    print(
        f"phases-smoke: OK — {len(rows)} phases, byte-coverage "
        f"x{(block.get('coverage') or {}).get('bytes_frac', 0):.2f}, "
        f"{len(jrows)} jsonl rows"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
