"""``make trace-smoke``: run the ``plans/chaos`` smoke composition
(which declares ``[global.run.trace]`` + telemetry) on the CPU backend
and assert the flight-recorder + latency-histogram contract end-to-end:

- the run completes and writes ``sim_trace.jsonl`` with schema-valid
  events scoped to the declared lanes, counting what the journal claims;
- ``trace_events.json`` is valid Chrome trace-event JSON (loads in
  Perfetto): a ``traceEvents`` list whose entries carry name/ph/pid/tid,
  with one named track per traced instance;
- the scheduled chaos is visible IN the trace (crash + restart status
  transitions on the crashed lanes, and fault_dropped send fates);
- the journal carries per-group delivery-latency percentiles whose
  histogram totals conserve (Σ bins == delivered), and ``tg stats``
  renders them;
- determinism: a second run of the same composition produces the
  identical event stream (modulo the run id).

Exits non-zero with a readable message on any violation. Self-contained:
temporary $TESTGROUND_HOME, CPU backend — safe in CI (mirrors
``tools/chaos_smoke.py``).
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def fail(msg: str) -> "None":
    print(f"trace-smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def _run_once(engine, comp, manifest, sources):
    import time

    from testground_tpu.engine import State

    tid = engine.queue_run(comp, manifest, sources_dir=sources)
    deadline = time.time() + 300
    while time.time() < deadline:
        t = engine.get_task(tid)
        if t is not None and t.state().state in (
            State.COMPLETE,
            State.CANCELED,
        ):
            return t
        time.sleep(0.05)
    fail(f"task {tid} did not finish within 300s")


def _read_events(env, task):
    from testground_tpu.sim.trace import TRACE_FILE

    path = os.path.join(env.dirs.outputs(), "chaos", task.id, TRACE_FILE)
    if not os.path.isfile(path):
        fail(f"{TRACE_FILE} was not written ({path})")
    events = []
    with open(path) as f:
        for i, line in enumerate(f):
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError as e:
                fail(f"line {i + 1} is not JSON: {e}")
    if not events:
        fail(f"{TRACE_FILE} is empty")
    return events


def main() -> int:
    os.environ["TESTGROUND_HOME"] = tempfile.mkdtemp(prefix="tg-trace-")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from testground_tpu.api import TestPlanManifest, load_composition
    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.config import EnvConfig
    from testground_tpu.engine import Engine, EngineConfig, Outcome
    from testground_tpu.runners.pretty import render_telemetry_summary
    from testground_tpu.sim.runner import SimJaxRunner
    from testground_tpu.sim.trace import TRACE_EVENTS_FILE

    plan_dir = os.path.join(REPO_ROOT, "plans", "chaos")
    comp_path = os.path.join(plan_dir, "_compositions", "smoke.toml")
    manifest = TestPlanManifest.load_file(
        os.path.join(plan_dir, "manifest.toml")
    )

    env = EnvConfig.load()
    engine = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    engine.start_workers()
    try:
        tasks = [
            _run_once(engine, load_composition(comp_path), manifest, plan_dir)
            for _ in range(2)  # second run pins determinism
        ]
    finally:
        engine.stop()

    task = tasks[0]
    if task.outcome() != Outcome.SUCCESS:
        fail(f"run outcome {task.outcome().value}: {task.error}")
    journal = task.result["journal"]

    # --- journal trace section vs the event stream on disk
    trace_j = journal.get("trace") or {}
    if trace_j.get("instances") != 3:
        fail(f"journal trace.instances = {trace_j.get('instances')} != 3")
    events = _read_events(env, task)
    if len(events) != trace_j.get("events"):
        fail(
            f"{len(events)} jsonl events != journal count "
            f"{trace_j.get('events')}"
        )
    lanes = {e["instance"] for e in events}
    if not lanes <= {0, 1, 2}:
        fail(f"events leaked outside the declared lanes 0:3: {lanes}")
    for key in ("tick", "instance", "group", "event"):
        if any(key not in e for e in events):
            fail(f"an event is missing the {key!r} field")

    # --- the scheduled chaos is visible in the trace: the crashed pair
    # must show crash AND restart status transitions, and the windows
    # must kill at least one traced send
    crashes = {
        e["instance"]
        for e in events
        if e["event"] == "status" and e.get("status") == "crash"
    }
    revivals = {
        e["instance"]
        for e in events
        if e["event"] == "status"
        and e.get("prev") == "crash"
        and e.get("status") == "running"
    }
    if crashes != {0, 1} or revivals != {0, 1}:
        fail(
            f"crash/restart transitions not recorded for lanes 0:2 "
            f"(crashes={crashes}, revivals={revivals})"
        )
    fates = {e.get("fate") for e in events if e["event"] == "send"}
    if "fault_dropped" not in fates:
        fail(f"no traced send with fate=fault_dropped (saw {fates})")

    # --- Chrome trace export loads as valid trace-event JSON
    ct_path = os.path.join(
        env.dirs.outputs(), "chaos", task.id, TRACE_EVENTS_FILE
    )
    try:
        with open(ct_path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        fail(f"{TRACE_EVENTS_FILE} is not valid JSON: {e}")
    te = doc.get("traceEvents")
    if not isinstance(te, list) or not te:
        fail("traceEvents is missing or empty")
    for ev in te:
        for key in ("name", "ph", "pid", "tid"):
            if key not in ev:
                fail(f"trace event missing {key!r}: {ev}")
    tracks = {
        ev["tid"] for ev in te if ev.get("name") == "thread_name"
    }
    if tracks != {0, 1, 2}:
        fail(f"expected one named track per traced instance, got {tracks}")

    # --- latency percentiles: journaled, conserving, and rendered
    latency = (journal.get("sim") or {}).get("latency") or {}
    if "all" not in latency or not latency["all"].get("count"):
        fail(f"journal sim.latency missing or empty: {latency}")
    for q in ("p50_ms", "p95_ms", "p99_ms"):
        if q not in latency["all"]:
            fail(f"latency percentile {q} missing: {latency['all']}")
    if latency["all"]["count"] != journal["sim"]["msgs_delivered"]:
        fail(
            "Σ latency bins {c} != delivered {d} — histogram "
            "conservation violated".format(
                c=latency["all"]["count"],
                d=journal["sim"]["msgs_delivered"],
            )
        )
    rendered = render_telemetry_summary(task.stats_payload())
    if "p50=" not in rendered or "latency all" not in rendered:
        fail(f"tg stats output lacks the latency section:\n{rendered}")

    # --- determinism: same seed + schedule → identical event stream
    strip = lambda evs: [  # noqa: E731
        {k: v for k, v in e.items() if k != "run"} for e in evs
    ]
    if strip(events) != strip(_read_events(env, tasks[1])):
        fail("two runs of the same composition produced different event "
             "streams — the flight recorder broke determinism")

    print(
        "trace-smoke: OK — {e} events from 3 instances (crash/restart "
        "transitions + fault_dropped fates recorded), Perfetto export "
        "valid ({t} trace events), latency p50/p95/p99 = "
        "{p50}/{p95}/{p99} ms over {n} deliveries, deterministic".format(
            e=len(events),
            t=len(te),
            p50=latency["all"]["p50_ms"],
            p95=latency["all"]["p95_ms"],
            p99=latency["all"]["p99_ms"],
            n=latency["all"]["count"],
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
