"""``make fleet-smoke``: the control-plane observability contract
(docs/OBSERVABILITY.md "Control plane") end-to-end on the CPU backend:

- a ``network:ping-pong`` run submitted with a client-minted
  traceparent exports ``task_spans.jsonl`` as a SINGLE connected tree —
  the submitter's span is the root, every parent id resolves, and the
  executor's ``run_spans.jsonl`` rows join under the ``execute`` span
  carrying the same trace id;
- ``task_trace.json`` is valid Chrome trace-event JSON (loads in
  Perfetto) with one event per span;
- the daemon event journal records the lifecycle in causal order
  (scheduled < claimed < started < finished) with monotonic seq and the
  task's trace ids on every record;
- the ``tg_fleet_*`` Prometheus family renders grammatically and
  conserves: Σ ``tg_fleet_tasks`` equals the full task-store count even
  when per-task series are truncated, and the queue-wait histogram
  buckets are cumulative ending at ``+Inf == count``;
- ``tg top``'s renderer produces the fleet view from the same payload
  ``GET /fleet`` serves.

Exits non-zero with a readable message on any violation. Self-contained:
temporary $TESTGROUND_HOME, CPU backend — safe in CI (mirrors
``tools/trace_smoke.py``).
"""

import json
import os
import re
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def fail(msg: str) -> "None":
    print(f"fleet-smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def _load_spans(path):
    if not os.path.isfile(path):
        fail(f"task_spans.jsonl was not written ({path})")
    spans = []
    with open(path) as f:
        for i, line in enumerate(f):
            try:
                spans.append(json.loads(line))
            except json.JSONDecodeError as e:
                fail(f"span line {i + 1} is not JSON: {e}")
    if not spans:
        fail("task_spans.jsonl is empty")
    return spans


def _check_tree(spans, ctx):
    ids = {s["span_id"] for s in spans}
    if len(ids) != len(spans):
        fail("duplicate span ids in task_spans.jsonl")
    roots = [s for s in spans if not s["parent_id"]]
    if len(roots) != 1:
        fail(f"expected one root span, got {[s['name'] for s in roots]}")
    if roots[0]["name"] != "submit" or roots[0]["span_id"] != ctx.span_id:
        fail("the tree is not rooted at the submitter's span")
    for s in spans:
        if s["parent_id"] and s["parent_id"] not in ids:
            fail(f"orphan span {s['name']}: parent {s['parent_id']}")
        if s["trace_id"] != ctx.trace_id:
            fail(f"span {s['name']} left the trace ({s['trace_id']})")
    kinds = {s["kind"] for s in spans}
    if not {"lifecycle", "run"} <= kinds:
        fail(f"missing span kinds: have {sorted(kinds)}")
    execute = next(s for s in spans if s["name"] == "execute")
    run_rows = [s for s in spans if s["kind"] == "run"]
    if not any(s["parent_id"] == execute["span_id"] for s in run_rows):
        fail("no executor span is parented under execute")


def _check_journal(path, task_id, trace_id):
    if not os.path.isfile(path):
        fail(f"daemon_events.jsonl was not written ({path})")
    rows = [json.loads(line) for line in open(path)]
    seqs = [r["seq"] for r in rows]
    if seqs != sorted(seqs) or len(set(seqs)) != len(seqs):
        fail("journal seq is not strictly monotonic")
    types = [r["type"] for r in rows if r["task"] == task_id]
    order = ["task.scheduled", "task.claimed", "task.started",
             "task.finished"]
    idx = []
    for t in order:
        if t not in types:
            fail(f"journal is missing {t} for the run")
        idx.append(types.index(t))
    if idx != sorted(idx):
        fail(f"journal lifecycle out of order: {types}")
    for r in rows:
        if r["task"] == task_id and r["trace_id"] != trace_id:
            fail(f"journal record {r['type']} lost the trace id")
        if not (r["ts_wall_ns"] > 0 and r["ts_mono_ns"] > 0):
            fail(f"journal record {r['type']} is missing a clock")


_LINE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? "
    r"(-?[0-9.e+-]+|\+Inf)$"
)


def _check_prometheus(engine):
    from testground_tpu.metrics.prometheus import render_prometheus

    tasks = engine.tasks()
    text = render_prometheus(tasks, fleet=engine.fleet_info())
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        if not _LINE_RE.match(line):
            fail(f"exposition grammar violation: {line!r}")
    states = dict(
        re.findall(r'tg_fleet_tasks\{state="(\w+)"\} (\d+)', text)
    )
    total = sum(int(v) for v in states.values())
    if total != len(tasks):
        fail(
            f"conservation: Σ tg_fleet_tasks = {total} "
            f"!= store count {len(tasks)}"
        )
    buckets = re.findall(
        r'tg_fleet_queue_wait_seconds_bucket\{le="([^"]+)"\} (\d+)', text
    )
    counts = [int(c) for _, c in buckets]
    if counts != sorted(counts):
        fail("queue-wait histogram buckets are not cumulative")
    if not buckets or buckets[-1][0] != "+Inf":
        fail("queue-wait histogram does not end at +Inf")
    m = re.search(r"tg_fleet_queue_wait_seconds_count (\d+)", text)
    if m is None or int(m.group(1)) != counts[-1]:
        fail("queue-wait +Inf bucket != _count")


def main() -> int:
    os.environ["TESTGROUND_HOME"] = tempfile.mkdtemp(prefix="tg-fleet-")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from testground_tpu.api import (
        Composition,
        Global,
        Group,
        Instances,
        TestPlanManifest,
        generate_default_run,
    )
    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.config import EnvConfig
    from testground_tpu.engine import Engine, EngineConfig, Outcome, State
    from testground_tpu.engine.tracetree import (
        TASK_SPANS_FILE,
        TASK_TRACE_FILE,
    )
    from testground_tpu.runners.pretty import (
        render_fleet,
        render_lifecycle_tree,
    )
    from testground_tpu.sim.runner import SimJaxRunner
    from testground_tpu.tracectx import TraceContext

    plan_dir = os.path.join(REPO_ROOT, "plans", "network")
    manifest = TestPlanManifest.load_file(
        os.path.join(plan_dir, "manifest.toml")
    )
    comp = generate_default_run(
        Composition(
            global_=Global(
                plan="network",
                case="ping-pong",
                builder="sim:plan",
                runner="sim:jax",
            ),
            groups=[Group(id="all", instances=Instances(count=2))],
        )
    )
    comp.global_.run_config.update({"chunk": 16})

    env = EnvConfig.load()
    engine = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    engine.start_workers()
    try:
        ctx = TraceContext.mint()
        tid = engine.queue_run(
            comp,
            manifest,
            sources_dir=plan_dir,
            trace_parent=ctx.to_traceparent(),
        )
        deadline = time.time() + 300
        while time.time() < deadline:
            t = engine.get_task(tid)
            if t is not None and t.state().state in (
                State.COMPLETE,
                State.CANCELED,
            ):
                break
            time.sleep(0.05)
        else:
            fail(f"task {tid} did not finish within 300s")
        if t.outcome() != Outcome.SUCCESS:
            fail(f"run outcome {t.outcome().value}: {t.error}")
        if t.trace.get("trace_id") != ctx.trace_id:
            fail("the task record did not adopt the submitted trace id")

        run_dir = os.path.join(env.dirs.outputs(), "network", t.id)
        spans = _load_spans(os.path.join(run_dir, TASK_SPANS_FILE))
        _check_tree(spans, ctx)
        print(f"fleet-smoke: span tree connected ({len(spans)} spans)")

        trace = json.load(open(os.path.join(run_dir, TASK_TRACE_FILE)))
        events = trace.get("traceEvents")
        if not isinstance(events, list) or len(events) != len(spans):
            fail("task_trace.json does not mirror the span file")
        for e in events:
            if not {"name", "ph", "pid", "tid"} <= set(e):
                fail(f"malformed Perfetto event: {e}")
        print("fleet-smoke: Perfetto export OK")

        _check_journal(engine.events.path, t.id, ctx.trace_id)
        print("fleet-smoke: event journal ordered + traced")

        _check_prometheus(engine)
        print("fleet-smoke: tg_fleet_* conserves + renders")

        view = render_fleet(engine.fleet_payload())
        if "workers" not in view or "queue depth" not in view:
            fail("render_fleet produced no fleet header")
        tree = render_lifecycle_tree(spans)
        for name in ("submit", "queued", "claim", "execute"):
            if name not in tree:
                fail(f"lifecycle tree render is missing {name}")
        print("fleet-smoke: tg top + tg trace --lifecycle render OK")
    finally:
        engine.stop()

    print("fleet-smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
