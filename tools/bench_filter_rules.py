"""Measure the range-rule filter overhead (PERF.md "Range-rule filters").

Ring traffic with K rules per instance REFRESHED EVERY TICK (worst case:
pays both the lookup and the full [K, 3, N] reconfiguration each tick)
vs the same ring with plain latency shaping. Run on the target backend:

    python tools/bench_filter_rules.py [--sizes 65536 131072 1048576]

The lookup is intentionally written in `sim/net.py` as o-fold TILES of
src-indexed rows (like the egress reads): the same logic written as
per-message gathers measured 11x at 64k on TPU — 3K scalar-core gathers
of m lanes — vs ~1.06x for the tiled form.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from testground_tpu.api import RunGroup  # noqa: E402
from testground_tpu.sim.api import (  # noqa: E402
    FILTER_ACCEPT,
    FILTER_REJECT,
    RUNNING,
    Outbox,
    SimTestcase,
)
from testground_tpu.sim.engine import SimProgram, build_groups  # noqa: E402


def make(n, mode, k):
    class Ring(SimTestcase):
        SHAPING = (
            ("latency",) if mode == "plain" else ("latency", "filter_rules")
        )
        FILTER_RULES = 0 if mode == "plain" else k
        MSG_WIDTH = 1
        OUT_MSGS = 1
        IN_MSGS = 2
        MAX_LINK_TICKS = 8
        DEFAULT_LINK = (2.0, 0, 0, 0, 0, 0, 0)

        def init(self, env):
            return {"received": jnp.int32(0)}

        def step(self, env, state, inbox, sync, t):
            n_ = env.test_instance_count
            succ = jnp.mod(env.global_seq + 1, n_)
            ob = Outbox.single(succ, jnp.asarray([1]), True, 1, 1)
            kw = {}
            if mode != "plain":
                # K-1 never-matching ranges + one explicit Accept —
                # every pass must evaluate, nothing short-circuits
                kw = dict(
                    net_rules=self.filter_rules(
                        *[
                            (succ + 2 + i, succ + 2 + i, FILTER_REJECT)
                            for i in range(k - 1)
                        ],
                        (0, n_, FILTER_ACCEPT),
                    ),
                    net_rules_valid=True,
                )
            return self.out(
                {"received": state["received"] + inbox.count},
                status=RUNNING,
                outbox=ob,
                **kw,
            )

    groups = build_groups([RunGroup(id="all", instances=n, parameters={})])
    return SimProgram(Ring(), groups, tick_ms=1.0, chunk=256)


def measure(n, mode, k):
    prog = make(n, mode, k)
    carry = jax.jit(lambda: prog.init_carry(0))()
    fn = prog.compiled_chunk()
    carry, _ = fn(carry)
    warm = int(np.asarray(carry.t))  # D2H sync (block_until_ready may
    # not block on remotely-tunneled backends)
    t0 = time.perf_counter()
    for _ in range(8):
        carry, _ = fn(carry)
    ticks = int(np.asarray(carry.t)) - warm
    wall = time.perf_counter() - t0
    return wall / ticks * 1e6  # µs/tick


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--sizes", type=int, nargs="+", default=[65536, 131072]
    )
    ap.add_argument("--rules", type=int, default=8)
    args = ap.parse_args()
    for n in args.sizes:
        a = measure(n, "plain", args.rules)
        b = measure(n, "rules", args.rules)
        print(
            f"n={n}: plain {a:.0f} us/tick, filter_rules(K={args.rules}) "
            f"{b:.0f} us/tick, overhead {b / a:.2f}x"
        )


if __name__ == "__main__":
    main()
