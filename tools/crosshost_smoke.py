"""``make crosshost-smoke``: the cross-host control-plane proof
(docs/CROSSHOST.md, ISSUE 10 acceptance):

Phase 1 — two-"host" ping-pong, BOTH sync backends: one run's instances
split across two process groups as hosts (separate $TESTGROUND_HOME
each, engine-less, joining purely by sync-service address — the
``cluster_k8s.go:302`` pattern), exchanging addresses via pubsub,
rendezvousing via signal_and_wait, and ping-ponging over real TCP; plus
one kill/reconnect round: the sync service is partitioned (SIGSTOP)
while host A is mid-subscribe and host B is still CONNECTING, then
healed — both must complete through the bounded-reconnect path.

Phase 2 — the 3-"host" chaos cohort, one composition of three host-level
events driven against one shared sync service:

- **member-death**: a host parked on a barrier is SIGKILLed; the server
  evicts it (occupancy released) and publishes the eviction, and the
  survivors' degraded rendezvous completes instead of deadlocking;
- **sync-partition-and-heal**: the service is unreachable for a window
  (SIGSTOP) with a barrier armed and a subscription waiting, then
  healed; clients reconnect with backoff, re-arm the barrier, resume the
  subscription, and the round completes;
- **leader-death**: the leader host is SIGKILLed; the surviving member
  observes the eviction, classifies the typed ``SyncLostError`` with
  the cohort-fatal classifier (the PR 9 clean-exit path), and exits
  with a one-line diagnosis — exit code 0, no LOG(FATAL), no traceback.

Every event is journaled to ``crosshost_journal.jsonl`` (one record per
event with its observations). Exits non-zero with a readable message on
any violation. Self-contained: temporary $TESTGROUND_HOME, no jax —
safe in CI, budget well under 60 s.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

START = time.monotonic()
JOURNAL: list = []


def fail(msg: str) -> None:
    print(f"crosshost-smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def journal(phase: str, event: str, **observed) -> None:
    rec = {
        "phase": phase,
        "event": event,
        "t_rel_secs": round(time.monotonic() - START, 3),
        "observed": observed,
    }
    JOURNAL.append(rec)
    print(f"crosshost-smoke: [{phase}] {event} {observed}")


def wait_until(pred, timeout: float, what: str):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    fail(f"timed out after {timeout:.0f}s waiting for {what}")


# ------------------------------------------------------------ services


def spawn_service(backend: str, native_bin: str | None, idle: float = 3.0):
    """Standalone sync-service subprocess; returns (proc, host, port).
    evict-grace is tightened so real deaths announce fast while
    reconnects (which land in well under 0.5 s here) stay silent."""
    if backend == "python":
        code = (
            "from testground_tpu.sync.server import _main; "
            f"_main(['--port', '0', '--idle-timeout', '{idle}', "
            "'--evict-grace', '0.5'])"
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            env={**os.environ, "PYTHONPATH": REPO_ROOT},
        )
        parts = proc.stdout.readline().split()
        return proc, parts[1], int(parts[2])
    proc = subprocess.Popen(
        [native_bin, "--port", "0", "--idle-timeout", str(idle),
         "--evict-grace", "0.5"],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    parts = proc.stdout.readline().split()
    return proc, "127.0.0.1", int(parts[1])


# ------------------------------------------- phase 1: two-host ping-pong


def pingpong_instance(workdir, group, seq, run_id, host, port):
    from testground_tpu.sdk.runparams import RunParams

    out_dir = os.path.join(workdir, group, "outputs")
    params = RunParams(
        test_plan="network",
        test_case="ping-pong",
        test_run=run_id,
        test_instance_count=2,
        test_group_id=group,
        test_group_instance_count=1,
        test_outputs_path=out_dir,
        test_temp_path=os.path.join(workdir, group, "tmp"),
        test_instance_seq=seq,
        test_group_seq=0,
        sync_service_host=host,
        sync_service_port=port,
        sync_connect_timeout=1.0,
        sync_retry_attempts=60,
        sync_retry_deadline=30.0,
        sync_heartbeat=0.25,
    )
    env = {**os.environ, **params.to_env(), "PYTHONPATH": REPO_ROOT}
    artifact = os.path.join(REPO_ROOT, "plans", "network", "main.py")
    return subprocess.Popen(
        [sys.executable, artifact],
        env=env,
        cwd=os.path.dirname(artifact),
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )


def phase1(backend: str, native_bin, workdir: str) -> None:
    proc, host, port = spawn_service(backend, native_bin)
    journal("pingpong", f"service-started[{backend}]", address=f"{host}:{port}")
    try:
        run_id = f"pp-{backend}-{os.getpid()}"
        a = pingpong_instance(workdir, f"hostA-{backend}", 0, run_id, host, port)
        time.sleep(0.6)  # A is now mid-subscribe awaiting B's address
        os.kill(proc.pid, signal.SIGSTOP)  # the kill/reconnect round:
        journal("pingpong", f"partition[{backend}]", note="service SIGSTOPped")
        b = pingpong_instance(workdir, f"hostB-{backend}", 1, run_id, host, port)
        time.sleep(1.2)  # B's INITIAL connect retries; A's heartbeat trips
        os.kill(proc.pid, signal.SIGCONT)
        journal("pingpong", f"heal[{backend}]", note="service SIGCONTed")
        outs = {}
        for name, p in (("hostA", a), ("hostB", b)):
            try:
                out, err = p.communicate(timeout=45)
            except subprocess.TimeoutExpired:
                a.kill()
                b.kill()
                fail(f"{backend}: {name} did not finish the ping-pong")
            outs[name] = (p.returncode, out, err)
        for name, (rc, out, err) in outs.items():
            if rc != 0:
                fail(
                    f"{backend}: {name} exited {rc}\n--- stdout\n{out}"
                    f"\n--- stderr\n{err}"
                )
        if not any('"success"' in out for _, out, _ in outs.values()):
            fail(f"{backend}: no success events recorded")
        journal(
            "pingpong",
            f"complete[{backend}]",
            hosts={k: v[0] for k, v in outs.items()},
            reconnect_round="survived",
        )
    finally:
        if proc.poll() is None:
            try:
                os.kill(proc.pid, signal.SIGCONT)
            except OSError:
                pass
            proc.kill()
        proc.wait(timeout=10)


# ------------------------------------------ phase 2: 3-host chaos cohort

# One inline host program, role-driven: leader (0), member (1),
# victim (2). Coordination is pure sync-plane (barriers + pubsub +
# eviction events) — the host-side control plane under test.
HOST_SCRIPT = r"""
import os, sys, threading, time
sys.path.insert(0, os.environ["TG_REPO"])
from testground_tpu.sync import SyncClient, SyncRetry, SyncLostError

role, inst = sys.argv[1], int(sys.argv[2])
host, port, run = sys.argv[3], int(sys.argv[4]), sys.argv[5]
ns = f"run:{run}:"
retry = SyncRetry(connect_timeout=1.0, attempts=80, deadline_secs=40.0,
                  backoff_base=0.05, backoff_cap=0.4, heartbeat_secs=0.25)
c = SyncClient(host, port, namespace=ns, retry=retry,
               identity={"events_topic": ns + "__run_events__",
                         "group": "hosts", "instance": inst})

dead = set()
control = []

def drain(topic, sink):
    def loop():
        try:
            for entry in c.subscribe(topic):
                sink(entry)
        except Exception:
            pass
    threading.Thread(target=loop, daemon=True).start()

drain("__run_events__",
      lambda e: dead.add(int(e.get("instance", -1)))
      if isinstance(e, dict) and e.get("type") == "evicted" else None)
drain("control", lambda e: control.append(e))

def progress(msg):
    c.publish("progress", {"inst": inst, "msg": msg})

def rendezvous(name, expect, timeout=40.0):
    # degraded rendezvous: arrivals OR evictions cover the cohort — a
    # dead host must complete the round for the survivors, not wedge it
    seen = set()
    drain(name, lambda e: seen.add(int(e["arrived"])))
    c.publish(name, {"arrived": inst})
    deadline = time.time() + timeout
    while time.time() < deadline:
        if expect <= (seen | dead):
            return
        time.sleep(0.05)
    raise TimeoutError(f"rendezvous {name}: seen={seen} dead={dead}")

ALL = {0, 1, 2}
rendezvous("start", ALL)
progress("started")

if role == "victim":
    progress("parked")
    c.barrier("never", 9, timeout=120)  # killed while parked (occupancy)
    sys.exit(1)  # unreachable

# r1: member-death — the victim dies parked; we must complete anyway
rendezvous("r1", ALL)
progress("r1-done")

if role == "leader":
    # r2: arm the barrier BEFORE the partition so reconnect must re-arm it
    progress("r2-armed")
    c.signal_and_wait("r2b", 2, timeout=60)
    progress("r2-done")
    time.sleep(120)  # killed by the orchestrator (leader-death)
    sys.exit(1)  # unreachable

# member: wait for the healed-partition go signal (the subscription
# itself rides the partition via resubscribe-at-seq)
def _saw_go():
    return any(isinstance(e, dict) and e.get("go") == "r2" for e in control)

deadline = time.time() + 40
while time.time() < deadline and not _saw_go():
    time.sleep(0.05)
if not _saw_go():
    raise TimeoutError("member never saw the go-r2 control entry")
c.signal_and_wait("r2b", 2, timeout=60)
progress("r2-done")

# r3: leader-death — observe the eviction, classify it with the
# cohort-fatal classifier (the PR 9 clean-exit path), exit in one line
deadline = time.time() + 40
while time.time() < deadline and 0 not in dead:
    time.sleep(0.05)
if 0 not in dead:
    raise TimeoutError("member never observed the leader eviction")
progress("r3-observed")
err = SyncLostError("cohort leader evicted; coordination plane lost")
from testground_tpu.sim.cohort import _is_cohort_fatal
assert _is_cohort_fatal(err), "SyncLostError must classify cohort-fatal"
print("sync-host: cohort lost (leader died: SyncLostError) — exiting "
      "cleanly", flush=True)
os._exit(0)
"""


def spawn_host(role, inst, host, port, run_id):
    return subprocess.Popen(
        [sys.executable, "-c", HOST_SCRIPT, role, str(inst), host,
         str(port), run_id],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env={**os.environ, "TG_REPO": REPO_ROOT},
    )


def phase2(backend: str, native_bin) -> None:
    from testground_tpu.sync import SyncClient, SyncRetry

    proc, host, port = spawn_service(backend, native_bin)
    journal("chaos", f"service-started[{backend}]", address=f"{host}:{port}")
    run_id = f"chaos-{os.getpid()}"
    ns = f"run:{run_id}:"
    obs = SyncClient(
        host,
        port,
        namespace=ns,
        retry=SyncRetry(
            connect_timeout=1.0,
            attempts=80,
            deadline_secs=40.0,
            backoff_base=0.05,
            backoff_cap=0.4,
            heartbeat_secs=0.25,
        ),
    )
    progress: list = []
    evicted: list = []

    def _drain(topic, sink):
        def loop():
            try:
                for entry in obs.subscribe(topic):
                    sink(entry)
            except Exception:  # noqa: BLE001 — observer is best-effort
                pass

        threading.Thread(target=loop, daemon=True).start()

    _drain("progress", progress.append)
    _drain(
        "__run_events__",
        lambda e: evicted.append(int(e.get("instance", -1)))
        if isinstance(e, dict) and e.get("type") == "evicted"
        else None,
    )

    def saw(inst, msg):
        return any(
            p.get("inst") == inst and p.get("msg") == msg for p in progress
        )

    hosts = {
        0: spawn_host("leader", 0, host, port, run_id),
        1: spawn_host("member", 1, host, port, run_id),
        2: spawn_host("victim", 2, host, port, run_id),
    }
    try:
        wait_until(
            lambda: all(saw(i, "started") for i in (0, 1, 2)),
            20,
            "3-host cohort start",
        )
        journal("chaos", "cohort-started", hosts=3)

        # ---- event 1: member-death (victim SIGKILLed while parked)
        wait_until(lambda: saw(2, "parked"), 15, "victim parked on barrier")
        wait_until(
            lambda: obs.sync_stats(timeout=5).get("waiters", 0) >= 1,
            10,
            "victim's barrier occupancy visible",
        )
        waiters_before = obs.sync_stats(timeout=5)["waiters"]
        hosts[2].kill()
        hosts[2].wait(timeout=10)
        wait_until(lambda: 2 in evicted, 15, "victim eviction event")
        wait_until(
            lambda: saw(0, "r1-done") and saw(1, "r1-done"),
            20,
            "survivors completing the degraded r1 rendezvous",
        )
        journal(
            "chaos",
            "member-death",
            killed_instance=2,
            waiters_before_kill=waiters_before,
            eviction_published=True,
            survivors_completed_round=True,
        )

        # ---- event 2: sync-partition-and-heal (barrier armed across it)
        wait_until(lambda: saw(0, "r2-armed"), 15, "leader arming r2 barrier")
        os.kill(proc.pid, signal.SIGSTOP)
        t_partition = time.monotonic()
        journal("chaos", "sync-partition", note="service SIGSTOPped")
        time.sleep(1.5)
        os.kill(proc.pid, signal.SIGCONT)
        journal(
            "chaos",
            "sync-heal",
            window_secs=round(time.monotonic() - t_partition, 2),
        )
        obs.publish("control", {"go": "r2"})
        wait_until(
            lambda: saw(0, "r2-done") and saw(1, "r2-done"),
            30,
            "barrier re-armed across the partition completing",
        )
        journal(
            "chaos",
            "partition-healed-round-complete",
            barrier_rearmed=True,
            subscription_resumed=True,
        )

        # ---- event 3: leader-death (clean member exit, PR 9 path)
        hosts[0].kill()
        hosts[0].wait(timeout=10)
        wait_until(lambda: 0 in evicted, 15, "leader eviction event")
        wait_until(lambda: saw(1, "r3-observed"), 20, "member observing it")
        try:
            m_out, m_err = hosts[1].communicate(timeout=20)
        except subprocess.TimeoutExpired:
            hosts[1].kill()
            fail("member did not exit after leader death")
        if hosts[1].returncode != 0:
            fail(
                f"member exited {hosts[1].returncode} (want clean 0)\n"
                f"--- stdout\n{m_out}\n--- stderr\n{m_err}"
            )
        if "cohort lost (leader died" not in m_out:
            fail(f"member missing the one-line clean exit:\n{m_out}")
        for blob, where in ((m_out, "stdout"), (m_err, "stderr")):
            for marker in ("LOG(FATAL)", "Traceback", "FATAL"):
                if marker in blob:
                    fail(f"member {where} shows {marker!r}:\n{blob}")
        journal(
            "chaos",
            "leader-death",
            killed_instance=0,
            eviction_published=True,
            member_exit_code=0,
            member_clean_line=True,
        )
    finally:
        for p in hosts.values():
            if p.poll() is None:
                p.kill()
        obs.close()
        if proc.poll() is None:
            try:
                os.kill(proc.pid, signal.SIGCONT)
            except OSError:
                pass
            proc.kill()
        proc.wait(timeout=10)


def main() -> None:
    os.environ.setdefault("TESTGROUND_HOME", tempfile.mkdtemp(prefix="tg-xh-"))
    workdir = tempfile.mkdtemp(prefix="tg-xh-work-")

    native_bin = None
    try:
        from testground_tpu.native import build_syncsvc, native_available

        if native_available():
            native_bin = build_syncsvc(os.path.join(workdir, "bin"))
    except Exception as e:  # noqa: BLE001 — python backend still proves it
        print(f"crosshost-smoke: native backend unavailable: {e}")

    # phase 1 on BOTH backends (the acceptance demands backend parity)
    phase1("python", None, workdir)
    if native_bin:
        phase1("native", native_bin, workdir)
    else:
        print("crosshost-smoke: WARNING — no C++ toolchain; native "
              "ping-pong not exercised")

    # phase 2 prefers the native backend (a real separate server process)
    phase2("native" if native_bin else "python", native_bin)

    journal_path = os.path.join(workdir, "crosshost_journal.jsonl")
    with open(journal_path, "w") as f:
        for rec in JOURNAL:
            f.write(json.dumps(rec) + "\n")
    expected_events = {
        "member-death",
        "sync-partition",
        "sync-heal",
        "partition-healed-round-complete",
        "leader-death",
    }
    got_events = {r["event"] for r in JOURNAL if r["phase"] == "chaos"}
    missing = expected_events - got_events
    if missing:
        fail(f"journal missing chaos events: {missing}")

    total = time.monotonic() - START
    if total > 60:
        fail(f"smoke exceeded its 60s budget: {total:.1f}s")
    print(
        f"crosshost-smoke: PASS — {len(JOURNAL)} journaled events "
        f"({journal_path}), {total:.1f}s"
    )


if __name__ == "__main__":
    main()
