"""``make preempt-smoke``: the fleet controller's preemption contract
(docs/FLEET.md) end-to-end against a REAL daemon subprocess on the CPU
backend — live migration, SIGTERM drain chaos, priority eviction, and
admission-at-submit, with bit-equal completions:

- **live migration**: a running ``network:pingpong-sustained`` task hit
  by ``POST /preempt`` checkpoints at its next chunk boundary, requeues
  itself with ``resume_from`` pointing at its own newest snapshot, and
  completes with journal totals and an ident-stripped telemetry stream
  byte-equal to an uninterrupted baseline;
- **SIGTERM drain**: SIGTERM to the daemon checkpoints + requeues the
  running task, journals ``daemon.drain``, and exits 0; a restarted
  daemon rehydrates the queue and the task resumes to the same
  bit-equal completion;
- **priority eviction**: with one worker busy on a priority-0 run, a
  priority-5 arrival evicts it (``task.evicted``), runs to completion
  first, and the evictee auto-requeues and still completes bit-equal;
- **admission-at-submit**: a composition ``tg check`` rejects (here
  ``transport.unknown``) is refused at ``POST /run`` with the rule id
  in the error and a ``task.refused`` journal record — nothing queues;
- **observability**: ``tg_fleet_preemptions_total`` /
  ``tg_fleet_evictions_total`` / ``tg_fleet_refused_total`` count on
  ``GET /metrics``, the controller decisions ride
  ``daemon_events.jsonl``, and the migrated task's span tree stays
  singly-rooted with the resume point recorded.

Exits non-zero with a readable message on any violation. Self-contained:
temporary $TESTGROUND_HOME, CPU backend, daemon subprocesses on an
ephemeral port — safe in CI (mirrors ``tools/fleet_smoke.py``).
"""

import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def fail(msg: str) -> "None":
    print(f"preempt-smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _comp(name: str, priority_case: bool = False) -> dict:
    """The shared deterministic workload: identical composition every
    time so completions are comparable bit-for-bit. ``priority_case``
    swaps in a short un-checkpointed ping-pong (the evicting arrival)."""
    if priority_case:
        run_config = {"chunk": 16, "seed": 5, "max_ticks": 256}
        case, params = "pingpong-sustained", {"duration_ticks": "100"}
    else:
        run_config = {
            "chunk": 16,
            "seed": 5,
            "max_ticks": 1400,
            "telemetry": True,
            "checkpoint_chunks": 1,
            "checkpoint_keep": 3,
        }
        case, params = "pingpong-sustained", {"duration_ticks": "1200"}
    return {
        "metadata": {"name": name},
        "global": {
            "plan": "network",
            "case": case,
            "builder": "sim:plan",
            "runner": "sim:jax",
            "total_instances": 2,
            "run_config": run_config,
        },
        "groups": [
            {
                "id": "all",
                "instances": {"count": 2},
                "run": {"test_params": params},
            }
        ],
    }


class DaemonProc:
    """One ``tg daemon`` subprocess bound to a known port."""

    def __init__(self, home: str, port: int, log_path: str):
        self.log = open(log_path, "ab")
        env = dict(os.environ)
        env["TESTGROUND_HOME"] = home
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        self.proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "testground_tpu.cli.main",
                "daemon",
                "--listen",
                f"127.0.0.1:{port}",
            ],
            env=env,
            stdout=self.log,
            stderr=self.log,
        )
        self.endpoint = f"http://127.0.0.1:{port}"

    def wait_ready(self, client, deadline_secs: float = 60.0) -> None:
        deadline = time.time() + deadline_secs
        while time.time() < deadline:
            if self.proc.poll() is not None:
                fail(
                    f"daemon exited rc={self.proc.returncode} before "
                    f"serving (see {self.log.name})"
                )
            try:
                client.fleet()
                return
            except Exception:  # noqa: BLE001 — not up yet
                time.sleep(0.1)
        fail("daemon did not become ready within 60s")

    def sigterm_and_wait(self, deadline_secs: float = 120.0) -> int:
        self.proc.send_signal(signal.SIGTERM)
        try:
            rc = self.proc.wait(timeout=deadline_secs)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            fail("daemon did not exit within 120s of SIGTERM")
        self.log.close()
        return rc


def _wait_state(client, tid, states, deadline_secs=240.0, poll=0.05):
    deadline = time.time() + deadline_secs
    while time.time() < deadline:
        t = client.status(tid)
        if t["states"][-1]["state"] in states:
            return t
        time.sleep(poll)
    fail(f"task {tid} never reached {states} within {deadline_secs}s")


def _wait_done(client, tid, deadline_secs=240.0):
    return _wait_state(
        client, tid, ("complete", "canceled"), deadline_secs, poll=0.1
    )


def _journal_rows(home):
    path = os.path.join(home, "data", "daemon", "daemon_events.jsonl")
    if not os.path.isfile(path):
        fail(f"daemon_events.jsonl was not written ({path})")
    return [json.loads(line) for line in open(path)]


def _stream_rows(home, tid, name="sim_timeseries.jsonl"):
    path = os.path.join(home, "data", "outputs", "network", tid, name)
    if not os.path.isfile(path):
        fail(f"{name} missing for task {tid}")
    return [
        {k: v for k, v in json.loads(line).items() if k != "run"}
        for line in open(path)
    ]


_COMPARE_KEYS = (
    "ticks",
    "msgs_delivered",
    "msgs_sent",
    "msgs_enqueued",
    "msgs_dropped",
    "msgs_rejected",
    "msgs_in_flight",
)


def _assert_bit_equal(home, label, base_task, chaos_task):
    jb = base_task["result"]["journal"]["sim"]
    jc = chaos_task["result"]["journal"]["sim"]
    for key in _COMPARE_KEYS:
        if jb.get(key) != jc.get(key):
            fail(
                f"{label}: journal sim.{key} diverged — "
                f"{jc.get(key)} != baseline {jb.get(key)}"
            )
    rows_b = _stream_rows(home, base_task["id"])
    rows_c = _stream_rows(home, chaos_task["id"])
    if rows_b != rows_c:
        fail(
            f"{label}: telemetry streams diverged "
            f"({len(rows_c)} vs {len(rows_b)} rows)"
        )


def main() -> int:
    home = tempfile.mkdtemp(prefix="tg-preempt-")
    os.environ["TESTGROUND_HOME"] = home
    # one worker: eviction only triggers when every slot is busy
    with open(os.path.join(home, ".env.toml"), "w") as f:
        f.write("[daemon.scheduler]\nworkers = 1\n")

    from testground_tpu.client import Client, DaemonError

    port = _free_port()
    plan_dir = os.path.join(REPO_ROOT, "plans", "network")
    daemon = DaemonProc(home, port, os.path.join(home, "daemon-a.log"))
    client = Client(daemon.endpoint)
    daemon.wait_ready(client)
    if client.import_plan(plan_dir) != "network":
        fail("plan import failed")

    # ---- baseline: uninterrupted completion of the shared workload
    base_id = client.run(_comp("baseline"))
    base = _wait_done(client, base_id)
    if base["outcome"] != "success":
        fail(f"baseline outcome {base['outcome']}: {base.get('error')}")
    print(f"preempt-smoke: baseline complete ({base_id})")

    # ---- live migration: POST /preempt mid-run, auto-resume, bit-equal
    mig_id = client.run(_comp("migrate"))
    _wait_state(client, mig_id, ("processing",))
    res = client.preempt(mig_id)
    if not res.get("ok"):
        fail(f"POST /preempt refused a running task: {res}")
    mig = _wait_done(client, mig_id)
    if mig["outcome"] != "success":
        fail(f"migrated outcome {mig['outcome']}: {mig.get('error')}")
    if int(mig["trace"].get("preemptions", 0)) < 1:
        fail("migrated task records no preemption in its trace")
    _assert_bit_equal(home, "live migration", base, mig)
    rows = _journal_rows(home)
    mine = [r for r in rows if r.get("task") == mig_id]
    types = [r["type"] for r in mine]
    for needed in ("task.preempt_requested", "task.preempted",
                   "task.migrated", "task.resumed"):
        if needed not in types:
            fail(f"journal is missing {needed} for the migrated task")
    migrated = next(r for r in mine if r["type"] == "task.migrated")
    if migrated.get("resume_from") != mig_id:
        fail(
            "task.migrated does not point the resume at the task's own "
            f"snapshots: {migrated}"
        )
    if any(not r.get("trace_id") for r in mine):
        fail("a controller decision lost the task's trace id")
    # the span tree stays singly-rooted and records the resume point
    spans_path = os.path.join(
        home, "data", "outputs", "network", mig_id, "task_spans.jsonl"
    )
    spans = [json.loads(l) for l in open(spans_path)]
    roots = [s for s in spans if not s["parent_id"]]
    ids = {s["span_id"] for s in spans}
    if len(roots) != 1 or any(
        s["parent_id"] and s["parent_id"] not in ids for s in spans
    ):
        fail("the migrated task's span tree is not singly-rooted/connected")
    if "resume" not in {s["name"] for s in spans}:
        fail("the migrated task's span tree has no resume point")
    print("preempt-smoke: live migration bit-equal + journaled")

    # ---- priority eviction: a priority-5 arrival evicts the busy worker
    victim_id = client.run(_comp("victim"))
    _wait_state(client, victim_id, ("processing",))
    hi_id = client.run(_comp("hi", priority_case=True), priority=5)
    hi = _wait_done(client, hi_id)
    if hi["outcome"] != "success":
        fail(f"high-priority arrival outcome {hi['outcome']}")
    victim = _wait_done(client, victim_id)
    if victim["outcome"] != "success":
        fail(f"evictee outcome {victim['outcome']}: {victim.get('error')}")
    _assert_bit_equal(home, "priority eviction", base, victim)
    rows = _journal_rows(home)
    evicted = [r for r in rows if r["type"] == "task.evicted"]
    if not any(
        r.get("task") == victim_id and r.get("by") == hi_id for r in evicted
    ):
        fail(f"no task.evicted record for {victim_id} by {hi_id}: {evicted}")
    print("preempt-smoke: priority eviction bit-equal + journaled")

    # ---- admission-at-submit: tg check error rules refuse at POST /run
    bad = _comp("bad")
    bad["global"]["run_config"]["transport"] = "bogus"
    try:
        client.run(bad)
        fail("a composition tg check rejects was accepted at submit")
    except DaemonError as e:
        if "transport.unknown" not in str(e):
            fail(f"refusal does not name the tg check rule id: {e}")
    if not any(
        r["type"] == "task.refused"
        and "transport.unknown" in (r.get("rules") or [])
        for r in _journal_rows(home)
    ):
        fail("no task.refused journal record naming the rule")
    print("preempt-smoke: admission refused with tg check rule ids")

    # ---- counters on GET /metrics (daemon-lifetime, so check pre-restart)
    import urllib.request

    text = urllib.request.urlopen(
        daemon.endpoint + "/metrics", timeout=10
    ).read().decode()
    for metric, floor in (
        ("tg_fleet_preemptions_total", 2),  # migration + eviction
        ("tg_fleet_evictions_total", 1),
        ("tg_fleet_refused_total", 1),
    ):
        m = re.search(rf"^{metric} (\d+)$", text, re.M)
        if m is None or int(m.group(1)) < floor:
            fail(f"{metric} missing or below {floor} on GET /metrics")
    print("preempt-smoke: tg_fleet_* counters exported")

    # ---- SIGTERM chaos: drain checkpoints + requeues, restart resumes
    chaos_id = client.run(_comp("chaos"))
    _wait_state(client, chaos_id, ("processing",))
    rc = daemon.sigterm_and_wait()
    if rc != 0:
        fail(f"daemon exited rc={rc} on SIGTERM (graceful drain must be 0)")
    rows = _journal_rows(home)
    if not any(r["type"] == "daemon.drain" for r in rows):
        fail("SIGTERM drain journaled no daemon.drain record")
    if not any(
        r["type"] == "task.preempted" and r.get("task") == chaos_id
        for r in rows
    ):
        fail("SIGTERM drain did not checkpoint + preempt the running task")
    daemon_b = DaemonProc(home, port, os.path.join(home, "daemon-b.log"))
    client = Client(daemon_b.endpoint)
    daemon_b.wait_ready(client)
    chaos = _wait_done(client, chaos_id)
    if chaos["outcome"] != "success":
        fail(
            f"post-restart resume outcome {chaos['outcome']}: "
            f"{chaos.get('error')}"
        )
    if int(chaos["trace"].get("preemptions", 0)) < 1:
        fail("chaos task records no preemption in its trace")
    _assert_bit_equal(home, "SIGTERM chaos", base, chaos)
    rc = daemon_b.sigterm_and_wait()
    if rc != 0:
        fail(f"idle daemon exited rc={rc} on SIGTERM")
    print("preempt-smoke: SIGTERM drain + restart resume bit-equal")

    print(
        "preempt-smoke: OK — live migration, priority eviction, "
        "admission refusal, SIGTERM drain chaos all bit-equal + journaled"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
