"""Sync-plane stats contract smoke (docs/OBSERVABILITY.md "Sync plane").

The CI-sized slice of the fan-in bench (~200 clients, BOTH backends,
< 20 s — the full 1k-10k ramp stays manual: ``tools/bench_sync_fanin.py``).
Asserts the contracts the stats plane owes:

0. **mid-scale rung**: a 1k-client connect storm + flood + width-1000
   barrier storm + pubsub fanout passes on both backends through the
   real bench machinery (the event-loop rewrite cannot silently regress
   between the 200-client contract check and the manual 10k ramp);
1. **stats conservation**, per backend: Σ server-side op counters ==
   the client-side op count actually driven (signal flood + barrier
   storm + pubsub + the stats queries themselves — counted at dispatch,
   so a ``sync_stats`` reply includes itself);
2. **v2 wire shape**: both backends answer ``"v": 2`` with every
   counter-level parity block present (the field-for-field value parity
   is pinned by tests/test_sync_stats.py);
3. **surface reconciliation**, live through the real CLI: a
   ``tg sync-service --metrics-port`` scrape exposes ``tg_sync_*``
   series that match a ``tg sync-stats --json`` snapshot taken around
   it, and the heartbeat line appears on stderr;
4. **instrumentation A/B** at smoke scale: instrumented-vs-
   uninstrumented signal-flood throughput, printed, and asserted within
   a CI-tolerant bound (the tight 5% claim is benched and banked in
   PERF.md "Sync fan-in" where a quiet machine measures it).
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time
import urllib.request

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
sys.path.insert(0, _REPO)
sys.path.insert(0, _HERE)

import bench_sync_fanin as B  # noqa: E402 — the shared driver

from testground_tpu.sync.stats import (  # noqa: E402
    PARITY_FIELDS,
    fetch_sync_stats,
)

CLIENTS = 200
SIGNAL_OPS = 10
PUB_ENTRIES = 20
PUB_SUBS = 50


def check(cond, msg):
    if not cond:
        print(f"FAIL: {msg}", file=sys.stderr)
        raise SystemExit(1)
    print(f"ok: {msg}")


def drive_backend(backend: str) -> None:
    proc, (host, port) = B.spawn_backend(backend)
    try:
        before = fetch_sync_stats(host, port)
        check(before.get("v") == 2, f"{backend}: sync_stats answers v2")
        for block, fields in PARITY_FIELDS.items():
            got = before.get(block)
            check(
                isinstance(got, dict)
                and all(f in got for f in fields),
                f"{backend}: v2 {block} block carries {fields}",
            )

        errs: list[str] = []
        conns = B.connect_clients(
            host, port, CLIENTS, time.monotonic() + 30, errs
        )
        check(
            len(conns) == CLIENTS and not errs,
            f"{backend}: {CLIENTS} concurrent clients connected",
        )
        flood, errs = B.rr_phase(
            conns,
            SIGNAL_OPS,
            lambda i, k: {
                "id": k + 1,
                "op": "signal_entry",
                "state": f"smoke-{i % 8}",
            },
            time.monotonic() + 60,
        )
        check(
            len(flood) == CLIENTS * SIGNAL_OPS and not errs,
            f"{backend}: signal flood completed "
            f"({CLIENTS}x{SIGNAL_OPS} round-trips)",
        )
        storm, errs = B.rr_phase(
            conns,
            1,
            lambda i, k: {
                "id": 1,
                "op": "signal_and_wait",
                "state": "smoke-storm",
                "target": CLIENTS,
                "timeout": 60,
            },
            time.monotonic() + 60,
        )
        check(
            len(storm) == CLIENTS and not errs,
            f"{backend}: width-{CLIENTS} barrier storm released",
        )
        wall, delivered, errs = B.pubsub_phase(
            conns, PUB_SUBS, PUB_ENTRIES, "smoke-fan",
            time.monotonic() + 60,
        )
        check(
            delivered == PUB_SUBS * PUB_ENTRIES and not errs,
            f"{backend}: pubsub fanout delivered "
            f"{PUB_SUBS}x{PUB_ENTRIES} frames",
        )
        after = fetch_sync_stats(host, port)
        for s in conns:
            s.close()

        # conservation: Σ op-counter deltas == ops this smoke drove.
        # Counters tick at dispatch, so the 'after' query includes
        # itself: delta(sync_stats) == the 1 query between the two.
        driven = {
            "signal_entry": CLIENTS * SIGNAL_OPS,
            "signal_and_wait": CLIENTS,
            "subscribe": PUB_SUBS,
            "publish": PUB_ENTRIES,
            "sync_stats": 1,
        }
        delta = B._ops_delta(before, after)
        for op, want in driven.items():
            check(
                delta.get(op) == want,
                f"{backend}: conservation {op}: server {delta.get(op)} "
                f"== driven {want}",
            )
        stray = {
            op: n for op, n in delta.items() if n and op not in driven
        }
        check(not stray, f"{backend}: no unaccounted ops ({stray})")
        bar = after.get("barriers") or {}
        check(
            bar.get("released", 0) - (before.get("barriers") or {}).get(
                "released", 0
            )
            == CLIENTS,
            f"{backend}: every storm waiter accounted released",
        )
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def drive_cli_surfaces() -> None:
    """tg sync-service --metrics-port + heartbeat + tg sync-stats, live
    through the real CLI, with the scrape reconciled against the
    snapshot."""
    svc = subprocess.Popen(
        [
            sys.executable, "-m", "testground_tpu.cli.main",
            "sync-service", "--backend", "python", "--port", "0",
            "--metrics-port", "0", "--stats-interval", "1",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        cwd=_REPO,
    )
    try:
        metrics_url = listen = None
        deadline = time.monotonic() + 30
        while (not metrics_url or not listen) and time.monotonic() < deadline:
            line = svc.stdout.readline().strip()
            if line.startswith("METRICS "):
                metrics_url = line.split()[1]
            elif line.startswith("LISTENING "):
                listen = line.split()[1:]
        check(metrics_url and listen, "tg sync-service announced both ports")
        host, port = listen[0], int(listen[1])

        errs: list[str] = []
        conns = B.connect_clients(host, port, 20, time.monotonic() + 10, errs)
        B.rr_phase(
            conns, 5,
            lambda i, k: {"id": k + 1, "op": "signal_entry", "state": "cli"},
            time.monotonic() + 30,
        )
        # quiesce: no in-flight ops while snapshotting, so the scrape and
        # the snapshot can only differ by the probes themselves
        snap = fetch_sync_stats(host, port)
        scrape = urllib.request.urlopen(metrics_url, timeout=10).read().decode()
        for s in conns:
            s.close()
        check(
            re.search(r"^tg_sync_conns \d+$", scrape, re.M) is not None,
            "scrape exposes tg_sync_conns",
        )
        # reconcile every per-op counter: the scrape ran AFTER the
        # snapshot with only its own fetch between → sync_stats +1 —
        # except the --stats-interval 1 heartbeat also queries
        # sync_stats on its own clock, so THAT row gets a small window
        # instead of an exact pin; every other op is exact (nothing but
        # this smoke drives them)
        for op, want in (snap.get("ops") or {}).items():
            m = re.search(
                rf'^tg_sync_ops_total\{{op="{op}"\}} (\d+)$', scrape, re.M
            )
            got = int(m.group(1)) if m else None
            if op == "sync_stats":
                check(
                    got is not None and want + 1 <= got <= want + 4,
                    f"scrape reconciles with snapshot: {op} in "
                    f"[{want + 1}, {want + 4}] (heartbeat may tick), "
                    f"got {got}",
                )
            else:
                check(
                    got == want,
                    f"scrape reconciles with snapshot: {op} == {want}",
                )
        m = re.search(r"^tg_sync_barrier_parked_total (\d+)$", scrape, re.M)
        check(
            m is not None
            and int(m.group(1)) == (snap.get("barriers") or {}).get("parked"),
            "scrape reconciles barrier counters",
        )
        check(
            "tg_sync_op_duration_seconds_bucket" in scrape,
            "scrape exposes per-op duration histograms",
        )
        # heartbeat line on stderr: give the 1s interval two chances to
        # fire before shutting the service down
        time.sleep(2.5)
        svc.send_signal(2)  # SIGINT: flush + exit
        try:
            _, err = svc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            svc.kill()
            _, err = svc.communicate()
        check(
            "sync-stats: conns=" in err and "ops/s=" in err,
            "heartbeat line appears in the service log",
        )
    finally:
        if svc.poll() is None:
            svc.kill()


def drive_1k_rung(backend: str) -> None:
    """A mid-scale (1k-client) fan-in rung through the real bench
    machinery — the regression tripwire for the event-loop rewrite (the
    old thread-per-conn server passed 200 and collapsed at 10k; 1k is
    the cheapest rung that exercises storm coalescing + the connect
    backlog at scale)."""
    from testground_tpu.native import build_fanin_driver, native_available

    cfg = {
        "signal_ops": 5,
        "pub_subs": 50,
        "pub_entries": 10,
        "timeout": 60,
        "driver": "python",
    }
    if native_available():
        cfg["driver"] = "native"
        cfg["driver_bin"] = build_fanin_driver(
            os.path.join("/tmp", "tg-syncsvc-bench")
        )
    rec = B.run_rung(backend, 1000, 1 if cfg["driver"] == "native" else 4,
                     cfg, log=lambda *_: None)
    check(
        rec.get("outcome") == "pass",
        f"{backend}: 1k fan-in rung passes ({rec.get('outcome')}: "
        f"{(rec.get('errors') or ['ok'])[:2]})",
    )
    bar = rec.get("barrier") or {}
    check(
        bar.get("completed") == 1000,
        f"{backend}: width-1000 barrier storm fully released "
        f"(p99 {bar.get('p99_ms')}ms)",
    )
    res = rec.get("server_resources") or {}
    check(
        (res.get("open_fds_peak") or 0) >= 1000,
        f"{backend}: bench sampled server resources "
        f"(rss {res.get('rss_mb_peak')}MB, fds {res.get('open_fds_peak')})",
    )


def main() -> int:
    t0 = time.monotonic()
    B.raise_nofile()
    backends = ["python"]
    from testground_tpu.native import native_available

    if native_available():
        backends.append("native")
    else:
        print("note: no g++ — native backend skipped", file=sys.stderr)
    for backend in backends:
        drive_backend(backend)
    for backend in backends:
        drive_1k_rung(backend)
    drive_cli_surfaces()
    ab = B.run_ab(clients=100, reps=2, cfg={"signal_ops": 20, "timeout": 60})
    # CI boxes are noisy neighbors: assert a loose bound here; the tight
    # <5% claim is measured on a quiet box and banked in PERF.md
    check(
        ab["overhead_pct"] is not None and ab["overhead_pct"] < 25.0,
        f"instrumentation overhead sane ({ab['overhead_pct']}% < 25%)",
    )
    print(f"sync-fanin smoke PASS in {time.monotonic() - t0:.1f}s")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
