"""Measure cohort (multi-process) overhead on the virtual CPU mesh.

PERF.md's ICI throughput claim needs a bound on the FRAMEWORK's own
cohort overhead, independent of interconnect speed (VERDICT r4 weak #3):
this tool runs the same workload over the same GLOBAL device count as

- ``1proc``: one process owning all D virtual devices, and
- ``2proc``: a real jax.distributed cohort — leader child + one
  ``tg sim-worker`` — with D/2 devices per process (cross-process
  collectives ride gloo/TCP, the DCN stand-in),

and reports steady-state wall (journal ``wall_secs − compile_secs``)
plus the 2proc/1proc ratio. Identical global mesh ⇒ identical program
shapes; only the process boundary differs.

Usage:  python tools/bench_cohort_overhead.py [--devices 2]
Writes one JSON line per workload to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")

RUNNER = r"""
import json, os, sys, threading
from testground_tpu.api import RunGroup, RunInput
from testground_tpu.config import EnvConfig
from testground_tpu.rpc import discard_writer
from testground_tpu.sim.executor import SimJaxConfig, execute_sim_run

spec = json.loads(sys.argv[1])
env = EnvConfig.load(spec["home"])
cfg = SimJaxConfig(chunk=spec["chunk"], max_ticks=spec["max_ticks"])
if spec.get("coord"):
    cfg.coordinator_address = spec["coord"]
    cfg.num_processes = 2
    cfg.process_id = 0
job = RunInput(
    run_id="ovh", test_plan=spec["plan"], test_case=spec["case"],
    total_instances=spec["n"],
    groups=[RunGroup(id="all", instances=spec["n"],
                     artifact_path=os.path.join(spec["plans"], spec["plan"]),
                     parameters=spec["params"])],
    runner_config=cfg, env=env)
out = execute_sim_run(job, discard_writer(), threading.Event())
sim = out.result.journal["sim"]
print("OVH " + json.dumps({
    "outcome": out.result.outcome.value, "ticks": sim["ticks"],
    "wall": sim["wall_secs"], "compile": sim["compile_secs"],
    "devices": sim["devices"], "processes": sim.get("processes", 1),
}), flush=True)
sys.stdin.readline()
"""


def _env(home, device_count):
    return {
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        "HOME": os.environ.get("HOME", "/root"),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={device_count}",
        "TESTGROUND_HOME": str(home),
        "PYTHONPATH": REPO_ROOT,
    }


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _read_result(proc, timeout):
    deadline = time.time() + timeout
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line == "":
            raise RuntimeError("runner died: " + proc.stderr.read()[-2000:])
        if line.startswith("OVH "):
            return json.loads(line[4:])
    raise TimeoutError("no result from runner")


def measure(spec, devices, cohort, timeout=1800):
    home = tempfile.mkdtemp(prefix="tg-ovh-")
    spec = dict(spec, home=home, plans=PLANS)
    follower = None
    if cohort:
        port = _free_port()
        spec["coord"] = f"127.0.0.1:{port}"
    per_proc = devices // 2 if cohort else devices
    leader = subprocess.Popen(
        [sys.executable, "-c", RUNNER, json.dumps(spec)],
        env=_env(home, per_proc),
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )
    try:
        if cohort:
            deadline = time.time() + 120
            while time.time() < deadline:
                try:
                    with socket.create_connection(
                        ("127.0.0.1", port), timeout=1
                    ):
                        break
                except OSError:
                    time.sleep(0.5)
            follower = subprocess.Popen(
                [sys.executable, "-m", "testground_tpu.cli.main",
                 "sim-worker", "--coordinator", spec["coord"],
                 "--num-processes", "2", "--process-id", "1",
                 "--plans", PLANS, "--once"],
                env=_env(home, per_proc),
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
        res = _read_result(leader, timeout)
        leader.stdin.write("\n")
        leader.stdin.flush()
        leader.wait(timeout=120)
        if follower is not None:
            follower.wait(timeout=120)
        return res
    finally:
        for p in (leader, follower):
            if p is not None and p.poll() is None:
                p.kill()


WORKLOADS = [
    {
        "label": "storm@4k",
        "plan": "benchmarks", "case": "storm", "n": 4096,
        "params": {"conn_outgoing": "5", "conn_delay_ticks": "32",
                   "data_size_kb": "512"},
        "chunk": 16, "max_ticks": 512,
    },
    {
        "label": "pingpong-sustained@8k",
        "plan": "network", "case": "pingpong-sustained", "n": 8192,
        "params": {"duration_ticks": "100000", "latency_ms": "4",
                   "latency2_ms": "2", "reshape_every": "1000"},
        "chunk": 64, "max_ticks": 1024,
    },
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=2,
                    help="GLOBAL virtual device count (split in half "
                    "across the 2-process cohort)")
    args = ap.parse_args()
    for w in WORKLOADS:
        spec = {k: w[k] for k in
                ("plan", "case", "n", "params", "chunk", "max_ticks")}
        a = measure(spec, args.devices, cohort=False)
        b = measure(spec, args.devices, cohort=True)
        for r, name in ((a, "1proc"), (b, "2proc")):
            assert r["outcome"] in ("success", "failure"), (w["label"], r)
        sa = a["wall"] - a["compile"]
        sb = b["wall"] - b["compile"]
        print(json.dumps({
            "workload": w["label"], "devices": args.devices,
            "ticks": a["ticks"],
            "steady_1proc_secs": round(sa, 2),
            "steady_2proc_secs": round(sb, 2),
            "ratio_2proc_over_1proc": round(sb / max(sa, 1e-9), 3),
            "compile_1proc": round(a["compile"], 1),
            "compile_2proc": round(b["compile"], 1),
        }), flush=True)


if __name__ == "__main__":
    main()
