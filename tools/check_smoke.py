"""``make check-smoke``: the static-analysis plane's end-to-end contract
(docs/CHECKING.md) on the CPU backend:

- **clean pass**: ``tg check`` on the repo's own chaos smoke
  composition (faults + trace + telemetry + SLO, all compatible) exits
  0 with ZERO findings — including under ``--trace-plans``;
- **seeded-bad pass**: a composition combining four incompatible knobs
  (unknown transport, unknown bucket mode, unknown fault kind, SLO
  without telemetry) reports ALL of them in ONE pass with their stable
  rule ids, ``--json`` schema version 1, and exit code 1;
- **plan lints**: the deliberately-broken fixture plan
  (tests/fixtures/badplan) fires ``plan.traced-int`` (python int on a
  traced count under bucketing) and ``plan.host-callback``
  (jax.debug.print in the tick) under ``--trace-plans``;
- **solo-reason journal**: a ``pack=true`` run excluded from packing by
  its own knobs journals ``sim.pack.solo_reason`` and ``tg stats``
  renders it — the tenant-visible "why didn't my run pack".

Exits non-zero with a readable message on any violation. Self-contained:
temporary $TESTGROUND_HOME, CPU backend — safe in CI (mirrors the other
observability smokes).
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

BADPLAN = os.path.join(REPO_ROOT, "tests", "fixtures", "badplan")


def fail(msg: str) -> None:
    print(f"check-smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


BAD_COMPOSITION = """\
[metadata]
name = "seeded-bad"

[global]
plan = "chaos"
case = "chaos-barrier"
builder = "sim:plan"
runner = "sim:jax"

[global.run_config]
transport = "warp"
bucket = "sideways"

[[global.run.slo]]
metric = "drop_rate"
op = "<"
threshold = 0.1

[[groups]]
id = "all"

[groups.instances]
count = 8

[[groups.run.faults]]
kind = "meteor"
start_ms = 1.0
"""

BADPLAN_COMPOSITION = """\
[metadata]
name = "badplan-{case}"

[global]
plan = "badplan"
case = "{case}"
builder = "sim:plan"
runner = "sim:jax"

[global.run_config]
bucket = "auto"
bucket_ladder = "16,64"
# bucketing is single-device; without this the smoke's virtual 8-device
# mesh would disable it (rule buckets.mesh-disabled) and the padded
# trace — the traced-count contract's teeth — would never run
shard = false

[[groups]]
id = "all"

[groups.instances]
count = 5
"""

# every rule id the seeded-bad composition must name, in one pass
EXPECTED_BAD_RULES = {
    "transport.unknown",
    "buckets.mode-invalid",
    "faults.invalid",
    "slo.needs-telemetry",
}


def run_check(argv) -> tuple[int, str]:
    """Drive the REAL CLI (the exit-code contract is part of the smoke)
    with stdout captured."""
    import contextlib
    import io

    from testground_tpu.cli.main import main as tg_main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = tg_main(["check", *argv])
    return rc, buf.getvalue()


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="tg-check-smoke-")
    os.environ["TESTGROUND_HOME"] = os.path.join(tmp, "home")

    # ------------------------------------------------- 1. clean pass
    clean = os.path.join(REPO_ROOT, "plans", "chaos", "_compositions", "smoke.toml")
    rc, out = run_check([clean, "--json"])
    doc = json.loads(out)
    if rc != 0:
        fail(f"clean composition exited {rc}: {out}")
    if doc.get("version") != 1:
        fail(f"--json schema version is {doc.get('version')!r}, want 1")
    if doc["errors"] or doc["warnings"]:
        fail(f"clean composition has findings: {out}")
    rc, out = run_check([clean, "--trace-plans"])
    if rc != 0 or "ok (no findings)" not in out:
        fail(f"clean composition under --trace-plans: rc={rc} {out!r}")
    print("check-smoke: clean composition ok (0 findings, exit 0)")

    # -------------------------------------------- 2. seeded-bad pass
    bad_path = os.path.join(tmp, "seeded-bad.toml")
    with open(bad_path, "w") as f:
        f.write(BAD_COMPOSITION)
    # the plan resolves from the repo's plans/ dir (cwd-relative)
    os.chdir(REPO_ROOT)
    rc, out = run_check([bad_path, "--json"])
    if rc != 1:
        fail(f"seeded-bad composition exited {rc}, want 1: {out}")
    doc = json.loads(out)
    fired = {
        f["rule"]
        for comp in doc["compositions"]
        for f in comp["findings"]
        if f["severity"] == "error"
    }
    missing = EXPECTED_BAD_RULES - fired
    if missing:
        fail(
            f"seeded-bad composition missed rule(s) {sorted(missing)} "
            f"(fired: {sorted(fired)})"
        )
    for comp in doc["compositions"]:
        for f in comp["findings"]:
            for key in ("rule", "severity", "layer", "message"):
                if key not in f:
                    fail(f"--json finding missing key {key!r}: {f}")
    print(
        "check-smoke: seeded-bad composition ok — all of "
        f"{sorted(EXPECTED_BAD_RULES)} in one pass, exit 1"
    )

    # ------------------------------------------------- 3. plan lints
    from testground_tpu.api import TestPlanManifest, load_composition
    from testground_tpu.sim.check import check_composition

    manifest = TestPlanManifest.load_file(
        os.path.join(BADPLAN, "manifest.toml")
    )

    def check_case(case):
        p = os.path.join(tmp, f"bp-{case}.toml")
        with open(p, "w") as f:
            f.write(BADPLAN_COMPOSITION.format(case=case))
        return check_composition(
            load_composition(p),
            manifest,
            trace_plans=True,
            plan_sources=BADPLAN,
        )

    fs = check_case("int-on-count")
    if not any(f.rule == "plan.traced-int" for f in fs):
        fail(f"int-on-count did not fire plan.traced-int: {fs}")
    fs = check_case("debug-print")
    if not any(f.rule == "plan.host-callback" for f in fs):
        fail(f"debug-print did not fire plan.host-callback: {fs}")
    fs = check_case("clean")
    if fs:
        fail(f"badplan clean control fired findings: {fs}")
    print(
        "check-smoke: plan lints ok — traced-int + host-callback fire, "
        "clean control silent"
    )

    # ------------------------------------- 4. solo-reason journaling
    import time

    from testground_tpu.api import (
        Composition,
        Global,
        Group,
        Instances,
        generate_default_run,
    )
    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.config import EnvConfig
    from testground_tpu.engine import Engine, EngineConfig, State
    from testground_tpu.runners.pretty import render_telemetry_summary
    from testground_tpu.sim.runner import SimJaxRunner

    engine = Engine(
        EngineConfig(
            env=EnvConfig.load(),
            builders=[SimPlanBuilder()],
            runners=[SimJaxRunner()],
        )
    )
    engine.start_workers()
    try:
        comp = generate_default_run(
            Composition(
                global_=Global(
                    plan="placebo",
                    case="ok",
                    builder="sim:plan",
                    runner="sim:jax",
                ),
                groups=[Group(id="all", instances=Instances(count=2))],
            )
        )
        # pack requested, but checkpointing excludes it from admission
        comp.global_.run_config.update(
            {"pack": True, "checkpoint_chunks": 2, "max_ticks": 64}
        )
        manifest = TestPlanManifest.load_file(
            os.path.join(REPO_ROOT, "plans", "placebo", "manifest.toml")
        )
        tid = engine.queue_run(
            comp,
            manifest,
            sources_dir=os.path.join(REPO_ROOT, "plans", "placebo"),
        )
        deadline = time.time() + 180
        while time.time() < deadline:
            t = engine.get_task(tid)
            if t is not None and t.state().state in (
                State.COMPLETE,
                State.CANCELED,
            ):
                break
            time.sleep(0.1)
        else:
            fail("solo-reason run did not finish")
        pack = (
            (t.result or {}).get("journal", {}).get("sim", {}).get("pack")
        )
        if not pack or pack.get("packed") is not False:
            fail(f"solo run journaled no sim.pack block: {pack!r}")
        if "checkpoint" not in (pack.get("solo_reason") or ""):
            fail(
                "solo_reason does not name the checkpoint exclusion: "
                f"{pack!r}"
            )
        stats = render_telemetry_summary(t.stats_payload())
        if "solo" not in stats or "checkpoint" not in stats:
            fail(f"tg stats does not render the solo reason:\n{stats}")
    finally:
        engine.stop()
    print(
        "check-smoke: solo-reason ok — journal sim.pack.solo_reason "
        f"({pack['solo_reason']!r}) rendered by tg stats"
    )

    print(
        "check-smoke: OK — clean pass, seeded-bad all-rules-one-pass, "
        "plan lints, solo-reason journal"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
