"""``make mesh-smoke``: the sharded serving plane end-to-end on a
4-virtual-device CPU mesh (PERF.md "Sharded serving plane") —

1. **Bucketed + packed + meshed through the real CLI path**: two
   tenants at different live sizes (one 32 bucket rung, which divides
   the 4 peer shards) queued with ``bucket=auto pack=true mesh=4
   transport=auto`` against one engine — both must pack into one
   vmapped device program laid out on the mesh.
2. **The journal carries the placement**: each run journals
   ``sim.mesh {axes, shards, layout_table, cross_shard_bytes_est}``
   and a SCORED ``sim.transport`` decision (mesh arms priced from the
   cost model, not refused); the ``tg stats`` render shows the mesh
   line and the Prometheus exposition carries ``tg_mesh_shards`` plus
   the ``mesh`` label on ``tg_transport_resolved``.
3. **Bit-equality to one device**: each tenant's flow totals (ticks,
   delivered/sent/enqueued/dropped/rejected/in-flight, pub_dropped)
   match an unmeshed, unpacked solo run of the same seed exactly.

Exits non-zero with a readable message on any violation. Self-contained:
temporary $TESTGROUND_HOME, CPU backend with virtual devices — safe in
CI (mirrors ``tools/pack_smoke.py``).
"""

import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the virtual mesh: must be set before jax initializes anywhere
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4"
    ).strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

LADDER = "32"
MESH = "4"
# two live sizes, one 32 rung — 32 divides the 4 peer shards
TENANT_SIZES = (20, 24)
RUN_CFG = {
    "bucket": "auto",
    "bucket_ladder": LADDER,
    "transport": "auto",
    "max_ticks": 2048,
    "chunk": 16,
}
FLOW_KEYS = (
    "ticks",
    "msgs_delivered",
    "msgs_sent",
    "msgs_enqueued",
    "msgs_dropped",
    "msgs_rejected",
    "msgs_in_flight",
    "pub_dropped",
)


def fail(msg: str) -> None:
    print(f"mesh-smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def _comp(n: int, seed: int, *, mesh: str, pack: bool):
    from testground_tpu.api import (
        Composition,
        Global,
        Group,
        Instances,
        generate_default_run,
    )

    return generate_default_run(
        Composition(
            global_=Global(
                plan="network",
                case="ping-pong",
                builder="sim:plan",
                runner="sim:jax",
                run_config={
                    **RUN_CFG,
                    "mesh": mesh,
                    "pack": pack,
                    "seed": seed,
                },
            ),
            groups=[Group(id="all", instances=Instances(count=n))],
        )
    )


def _wait(engine, tids, budget=600):
    from testground_tpu.engine import State

    deadline = time.time() + budget
    while time.time() < deadline:
        done = [
            engine.get_task(t).state().state
            in (State.COMPLETE, State.CANCELED)
            for t in tids
        ]
        if all(done):
            return [engine.get_task(t) for t in tids]
        time.sleep(0.2)
    fail(f"tasks did not finish within {budget}s")


def main() -> int:
    home = tempfile.mkdtemp(prefix="tg-mesh-smoke-")
    os.environ["TESTGROUND_HOME"] = home
    os.makedirs(os.path.join(home, "plans"), exist_ok=True)
    shutil.copytree(
        os.path.join(REPO_ROOT, "plans", "network"),
        os.path.join(home, "plans", "network"),
    )
    sources = os.path.join(home, "plans", "network")

    import jax

    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < 4:
        fail(f"expected >= 4 virtual devices, found {len(jax.devices())}")

    from testground_tpu.api import TestPlanManifest
    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.config import EnvConfig
    from testground_tpu.engine import Engine, EngineConfig, Outcome
    from testground_tpu.metrics.prometheus import render_prometheus
    from testground_tpu.runners.pretty import render_telemetry_summary
    from testground_tpu.sim.runner import SimJaxRunner

    env = EnvConfig.load()
    manifest = TestPlanManifest.load_file(
        os.path.join(sources, "manifest.toml")
    )

    # ---- 1. the meshed batch: both tenants queued BEFORE the single
    # worker starts, so pack admission claims them as one meshed pack
    engine = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    engine.env.daemon.scheduler.workers = 1
    t0 = time.time()
    tids = [
        engine.queue_run(
            _comp(n, i, mesh=MESH, pack=True), manifest, sources_dir=sources
        )
        for i, n in enumerate(TENANT_SIZES)
    ]
    engine.start_workers()
    meshed = _wait(engine, tids)
    meshed_wall = time.time() - t0

    sims = []
    for task, n in zip(meshed, TENANT_SIZES):
        if task.outcome() != Outcome.SUCCESS:
            fail(f"meshed tenant n={n} outcome {task.outcome().value}: "
                 f"{task.error}")
        sim = (task.result.get("journal") or {}).get("sim") or {}
        sims.append(sim)
        mesh_block = sim.get("mesh") or {}
        if mesh_block.get("axes") != MESH:
            fail(f"n={n}: journal sim.mesh.axes != {MESH!r}: {mesh_block}")
        if int(mesh_block.get("shards") or 0) != 4:
            fail(f"n={n}: journal sim.mesh.shards != 4: {mesh_block}")
        if not mesh_block.get("layout_table"):
            fail(f"n={n}: journal sim.mesh has no layout_table")
        if int(mesh_block.get("cross_shard_bytes_est") or -1) < 0:
            fail(f"n={n}: bogus cross_shard_bytes_est: {mesh_block}")
        tr = sim.get("transport") or {}
        if tr.get("requested") != "auto" or not tr.get("reason"):
            fail(f"n={n}: sim.transport not a scored auto decision: {tr}")
        pk = sim.get("pack") or {}
        if int(pk.get("members") or 1) != len(TENANT_SIZES):
            fail(
                f"n={n}: expected one pack of {len(TENANT_SIZES)}, "
                f"journal sim.pack = {pk}"
            )

    stats = render_telemetry_summary(
        {"plan": "network", "case": "ping-pong", **meshed[0].result["journal"]}
    )
    if "mesh" not in stats:
        fail(f"tg stats render lacks the mesh line:\n{stats}")
    text = render_prometheus(meshed)
    if "\ntg_mesh_shards{" not in text:
        fail("tg_mesh_shards absent from the Prometheus exposition")
    if f'mesh="{MESH}"' not in text:
        fail("tg_transport_resolved lacks the mesh label")

    # ---- 2. the unmeshed, unpacked twins — bit-equality to one device
    engine.stop()
    engine = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    tids = [
        engine.queue_run(
            _comp(n, i, mesh="", pack=False), manifest, sources_dir=sources
        )
        for i, n in enumerate(TENANT_SIZES)
    ]
    engine.start_workers()
    solos = _wait(engine, tids)
    engine.stop()

    for task, solo, n in zip(meshed, solos, TENANT_SIZES):
        if solo.outcome() != Outcome.SUCCESS:
            fail(f"solo tenant n={n} outcome {solo.outcome().value}: "
                 f"{solo.error}")
        sim_m = (task.result.get("journal") or {}).get("sim") or {}
        sim_s = (solo.result.get("journal") or {}).get("sim") or {}
        for key in FLOW_KEYS:
            if sim_m.get(key) != sim_s.get(key):
                fail(
                    f"n={n}: meshed {key} != solo: "
                    f"{sim_m.get(key)} vs {sim_s.get(key)}"
                )
        if not sim_m.get("msgs_delivered"):
            fail(f"n={n}: the meshed run moved no traffic")

    print(
        f"mesh-smoke: OK — {len(TENANT_SIZES)} tenants bucketed+packed on "
        f"a {MESH}-shard mesh in {meshed_wall:.1f}s, transport "
        f"auto→{(sims[0].get('transport') or {}).get('resolved')}, "
        f"flow totals bit-equal to one device over "
        f"{sims[0].get('msgs_delivered')} delivered msgs"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
