"""``make checkpoint-smoke``: the checkpoint/resume plane's end-to-end
contract (docs/CHECKPOINT.md) on the CPU backend, driving the most
adversarial composition in the tree — the ``plans/chaos`` smoke (crash +
restart + link_flap + partition faults, flight recorder, warn-severity
SLO, telemetry) — so the snapshot must carry EVERY plane's state:

- **bit-identical continuation**: a run interrupted by a short tick
  budget at a chunk boundary, then resumed from its newest snapshot,
  must journal the same ticks / flow totals / fault counters / SLO
  breach totals as an uninterrupted run, with a byte-equal (ident-
  stripped) per-tick telemetry stream and SLO record stream;
- **bounded retention**: only the newest ``checkpoint_keep`` snapshots
  survive on disk;
- **provenance**: the resumed journal records what it resumed from, the
  ``tg stats`` table renders the checkpoint line, and the Prometheus
  exposition carries ``tg_checkpoint_*``;
- **loud fallback**: a truncated newest snapshot falls back to the
  previous retained one — the resume succeeds, journals what it
  skipped, and still lands bit-equal with the uninterrupted run;
- **loud refusal**: when EVERY retained snapshot is unloadable the
  resume fails with the typed CheckpointError — never resumes garbage.

Exits non-zero with a readable message on any violation. Self-contained:
temporary $TESTGROUND_HOME, CPU backend — safe in CI (mirrors
``tools/slo_smoke.py``)."""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def fail(msg: str) -> "None":
    print(f"checkpoint-smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def _run_once(engine, comp, manifest, sources):
    import time

    from testground_tpu.engine import State

    tid = engine.queue_run(comp, manifest, sources_dir=sources)
    deadline = time.time() + 300
    while time.time() < deadline:
        t = engine.get_task(tid)
        if t is not None and t.state().state in (
            State.COMPLETE,
            State.CANCELED,
        ):
            return t
        time.sleep(0.05)
    fail(f"task {tid} did not finish within 300s")


def _rows(env, task_id, name):
    path = os.path.join(env.dirs.outputs(), "chaos", task_id, name)
    if not os.path.isfile(path):
        return None
    out = []
    with open(path) as f:
        for i, line in enumerate(f):
            try:
                row = json.loads(line)
            except json.JSONDecodeError as e:
                fail(f"{name} line {i + 1} of {task_id} is not JSON: {e}")
            out.append({k: v for k, v in row.items() if k != "run"})
    return out


def main() -> int:
    os.environ["TESTGROUND_HOME"] = tempfile.mkdtemp(prefix="tg-ckpt-")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from testground_tpu.api import TestPlanManifest, load_composition
    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.config import EnvConfig
    from testground_tpu.engine import Engine, EngineConfig, Outcome
    from testground_tpu.metrics.prometheus import render_prometheus
    from testground_tpu.runners.pretty import render_telemetry_summary
    from testground_tpu.sim.checkpoint import CHECKPOINT_DIR
    from testground_tpu.sim.runner import SimJaxRunner

    plan_dir = os.path.join(REPO_ROOT, "plans", "chaos")
    comp_path = os.path.join(plan_dir, "_compositions", "smoke.toml")
    manifest = TestPlanManifest.load_file(
        os.path.join(plan_dir, "manifest.toml")
    )

    def comp_with(**run_cfg):
        comp = load_composition(comp_path)
        comp.global_.run_config.update(run_cfg)
        return comp

    env = EnvConfig.load()
    engine = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    engine.start_workers()
    try:
        # uninterrupted reference, checkpointing every chunk
        full = _run_once(
            engine,
            comp_with(checkpoint_chunks=1, checkpoint_keep=2),
            manifest,
            plan_dir,
        )
        # interrupted at tick 32 (a chunk boundary, mid-fault-schedule:
        # the partition is still open and the heal is still to come)
        cut = _run_once(
            engine,
            comp_with(checkpoint_chunks=1, checkpoint_keep=2, max_ticks=32),
            manifest,
            plan_dir,
        )
        # resumed with the full budget
        resumed = _run_once(
            engine,
            comp_with(
                checkpoint_chunks=1,
                checkpoint_keep=2,
                resume_from=cut.id,
            ),
            manifest,
            plan_dir,
        )
        # corrupt the newest snapshot, then resume again: loud fallback
        # to the previous retained snapshot, not a refusal
        import testground_tpu.sim.checkpoint as _ckpt_mod

        _ckpt_mod._RETRY_BASE_SECS = 0.01  # keep the smoke fast
        _ckpt_mod._RETRY_JITTER_SECS = 0.0
        ckpt_dir = os.path.join(
            env.dirs.outputs(), "chaos", cut.id, CHECKPOINT_DIR
        )
        names = sorted(os.listdir(ckpt_dir))
        if len(names) != 2:
            fail(
                f"retention: expected 2 snapshot(s) under {ckpt_dir} "
                f"(checkpoint_keep=2), found {names}"
            )
        newest = os.path.join(ckpt_dir, names[-1])
        with open(newest, "r+b") as f:
            f.truncate(os.path.getsize(newest) // 3)
        fellback = _run_once(
            engine,
            comp_with(checkpoint_chunks=1, resume_from=cut.id),
            manifest,
            plan_dir,
        )
        # corrupt EVERY retained snapshot, then try once more: typed
        # refusal, never garbage
        for name in names:
            path = os.path.join(ckpt_dir, name)
            with open(path, "r+b") as f:
                f.truncate(os.path.getsize(path) // 3)
        refused = _run_once(
            engine,
            comp_with(checkpoint_chunks=1, resume_from=cut.id),
            manifest,
            plan_dir,
        )
    finally:
        engine.stop()

    # ---- the uninterrupted reference behaves like chaos-smoke
    if full.outcome() != Outcome.SUCCESS:
        fail(f"reference run outcome {full.outcome().value}: {full.error}")
    jf = full.result["journal"]
    ck = jf["sim"].get("checkpoint") or {}
    if not ck.get("count"):
        fail(f"reference run journaled no snapshots: {ck}")
    if ck.get("bytes", 0) <= 0 or ck.get("write_ms", 0) <= 0:
        fail(f"checkpoint journal lacks bytes/write_ms gauges: {ck}")

    # ---- the cut run was interrupted mid-schedule, snapshots on disk
    jc = cut.result["journal"]
    if jc["sim"]["ticks"] != 32:
        fail(f"cut run executed {jc['sim']['ticks']} ticks, wanted 32")

    # ---- bit-identical continuation
    if resumed.outcome() != Outcome.SUCCESS:
        fail(
            f"resumed run outcome {resumed.outcome().value}: "
            f"{resumed.error}"
        )
    jr = resumed.result["journal"]
    res_ck = jr["sim"].get("checkpoint") or {}
    if (res_ck.get("resumed") or {}).get("from_run") != cut.id:
        fail(f"resumed journal lacks provenance: {res_ck}")
    for key in (
        "ticks",
        "msgs_delivered",
        "msgs_sent",
        "msgs_enqueued",
        "msgs_dropped",
        "msgs_rejected",
        "msgs_in_flight",
        "msgs_fault_dropped",
        "faults_crashed",
        "faults_restarted",
    ):
        if jr["sim"].get(key) != jf["sim"].get(key):
            fail(
                f"resumed vs uninterrupted journal sim.{key}: "
                f"{jr['sim'].get(key)} != {jf['sim'].get(key)}"
            )
    slo_f = (jf.get("slo") or {}).get("breaches")
    slo_r = (jr.get("slo") or {}).get("breaches")
    if slo_f != slo_r:
        fail(f"SLO breach totals diverged: resumed {slo_r} != full {slo_f}")
    tr_f = (jf.get("trace") or {}).get("events")
    tr_r = (jr.get("trace") or {}).get("events")
    if tr_f != tr_r:
        fail(f"flight-recorder event counts diverged: {tr_r} != {tr_f}")
    for name in ("sim_timeseries.jsonl", "sim_slo.jsonl"):
        rows_f = _rows(env, full.id, name)
        rows_r = _rows(env, resumed.id, name)
        if rows_f != rows_r:
            fail(
                f"{name} streams diverged between the resumed and the "
                f"uninterrupted run ({len(rows_r or [])} vs "
                f"{len(rows_f or [])} rows)"
            )

    # ---- surfaces: stats table + Prometheus gauges
    table = render_telemetry_summary(resumed.stats_payload())
    if "checkpoint" not in table or f"of run {cut.id}" not in table:
        fail(f"tg stats table has no checkpoint/resume line:\n{table}")
    text = render_prometheus([full], per_task_limit=10)
    for gauge in ("tg_checkpoint_count{", "tg_checkpoint_last_tick{"):
        if gauge not in text:
            fail(f"{gauge} missing from the Prometheus exposition")

    # ---- corrupt newest snapshot: loud fallback, still bit-equal
    if fellback.outcome() != Outcome.SUCCESS:
        fail(
            "resume with a truncated newest snapshot must fall back to "
            f"the previous one, got {fellback.outcome().value}: "
            f"{fellback.error}"
        )
    jfb = fellback.result["journal"]
    fb_res = (jfb["sim"].get("checkpoint") or {}).get("resumed") or {}
    fb = fb_res.get("fallback") or {}
    if fb.get("skipped") != [names[-1]] or not fb.get("error"):
        fail(
            f"fallback resume journaled no skipped-snapshot provenance: "
            f"{fb_res}"
        )
    for key in ("ticks", "msgs_delivered", "faults_crashed"):
        if jfb["sim"].get(key) != jf["sim"].get(key):
            fail(
                f"fallback-resumed vs uninterrupted journal sim.{key}: "
                f"{jfb['sim'].get(key)} != {jf['sim'].get(key)}"
            )

    # ---- every snapshot corrupt: refused loudly, typed
    if refused.outcome() != Outcome.FAILURE:
        fail(
            "resume with every snapshot truncated must FAIL, got "
            f"{refused.outcome().value}"
        )
    if "refusing to resume" not in (refused.error or ""):
        fail(
            f"refusal error is not the typed CheckpointError message: "
            f"{refused.error!r}"
        )

    print(
        "checkpoint-smoke: OK — {n} snapshot(s) (keep=2 enforced), cut at "
        "tick 32 mid-schedule, resumed run == uninterrupted run "
        "(journal + telemetry + SLO streams, {t} ticks), provenance + "
        "tg_checkpoint_* exported, truncated newest fell back loudly, "
        "all-corrupt refused loudly".format(n=ck["count"], t=jr["sim"]["ticks"])
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
